"""Comm-layer tests: codec round-trips (incl. the native C++ LZ codec),
framing, and a real cross-process remote worker driven by the dispatcher
over TCP — the reference's multi-machine mode exercised hermetically via
localhost (its own test affordance, SURVEY.md §4)."""

import os
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.comm import codec as codec_lib
from adapt_tpu.comm import native
from adapt_tpu.comm.framing import MSG_DATA, Message, recv_msg, send_msg
from conftest import chain_cfg, chain_pool, spawn_worker_proc


# -- native codec -----------------------------------------------------------


def test_native_build_and_roundtrip():
    data = (b"the quick brown fox " * 100) + os.urandom(64)
    comp = native.compress(data)
    assert native.decompress(comp, len(data)) == data
    # Repetitive data must actually compress.
    rep = b"ab" * 4096
    assert len(native.compress(rep)) < len(rep) // 4


def test_native_empty_and_tiny():
    for data in (b"", b"a", b"abcdefg", b"x" * 15):
        comp = native.compress(data)
        assert native.decompress(comp, len(data)) == data


def test_native_malformed_rejected():
    if native.load() is None:
        pytest.skip("no native toolchain")
    with pytest.raises(ValueError):
        native.decompress(b"Q\x10\x00\x00\x00garbage", 16)


@pytest.mark.parametrize("size", [1 << 10, 1 << 16, (1 << 20) + 17])
def test_native_large_random_and_structured(size):
    rng = np.random.default_rng(0)
    # float32 activations quantized to int16 (the zfp-codec path shape).
    x = (rng.standard_normal(size // 2)).astype(np.float16).tobytes()[:size]
    comp = native.compress(x)
    assert native.decompress(comp, len(x)) == x


# -- tensor codecs ----------------------------------------------------------


@pytest.mark.parametrize(
    "name,rtol",
    [
        ("none", 0),
        ("bf16", 1e-2),
        ("int8", 2e-2),
        ("zfp", 1e-2),
        ("lz", 0),
        ("int8dev", 2e-2),
    ],
)
def test_codec_roundtrip(name, rtol):
    rng = np.random.default_rng(1)
    x = rng.standard_normal((4, 32, 32, 8)).astype(np.float32)
    codec = codec_lib.get_codec(name)
    blob, meta = codec.encode(x)
    y = codec.decode(blob, meta)
    assert y.shape == x.shape and y.dtype == x.dtype
    if name in ("none", "lz"):
        np.testing.assert_array_equal(x, y)
    else:
        assert np.max(np.abs(x - y)) < rtol * max(1.0, np.max(np.abs(x)))


def test_lz_codec_lossless_any_dtype():
    """The weights-path codec must be bit-exact for every dtype a model
    carries (f32, bf16 params, int32 step counters in opt state)."""
    import ml_dtypes

    rng = np.random.default_rng(5)
    for arr in (
        rng.standard_normal((16, 16)).astype(np.float32),
        rng.standard_normal((7, 3)).astype(ml_dtypes.bfloat16),
        rng.integers(-100, 100, size=(12,)).astype(np.int32),
    ):
        codec = codec_lib.get_codec("lz")
        blob, meta = codec.encode(arr)
        y = codec.decode(blob, meta)
        assert y.dtype == arr.dtype
        np.testing.assert_array_equal(np.asarray(arr), np.asarray(y))


def test_int8dev_codec_matches_host_oracle():
    """The on-device (Pallas) codec must agree with the pure-jnp blockwise
    quantization oracle it re-expresses."""
    from adapt_tpu.ops.quantize import dequantize_reference, quantize_reference

    rng = np.random.default_rng(6)
    x = rng.standard_normal((3, 50, 17)).astype(np.float32) * 4.0
    codec = codec_lib.get_codec("int8dev")
    blob, meta = codec.encode(jnp.asarray(x))
    y = codec.decode(blob, meta)
    oracle = np.asarray(dequantize_reference(quantize_reference(jnp.asarray(x))))
    np.testing.assert_allclose(y, oracle, rtol=0, atol=1e-6)


def test_zfp_tolerance_honored():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((1024,)).astype(np.float32)
    for tol in (1e-2, 1e-3):
        codec = codec_lib.get_codec("zfp", tolerance=tol)
        blob, meta = codec.encode(x)
        y = codec.decode(blob, meta)
        # step = max(tol, absmax/32767); here absmax/32767 << tol, so the
        # round-off error is bounded by step/2 = tol/2.
        assert np.max(np.abs(x - y)) <= tol / 2 + 1e-7


def test_pack_unpack_self_describing():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    for name in codec_lib.CODECS:
        buf = codec_lib.pack(codec_lib.get_codec(name), x)
        y = codec_lib.unpack(buf)
        assert y.shape == x.shape
        if name == "none":
            np.testing.assert_array_equal(x, y)


def test_unknown_codec_rejected():
    with pytest.raises(ValueError, match="unknown codec"):
        codec_lib.get_codec("lz77max")


# -- framing ----------------------------------------------------------------


def test_framing_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    try:
        msg = Message(MSG_DATA, 3, 123456789, 2, b"\x00" * 100_000)
        t = threading.Thread(target=send_msg, args=(a, msg))
        t.start()
        got = recv_msg(b)
        t.join()
        assert got == msg
    finally:
        a.close()
        b.close()


def test_framing_peer_close_raises():
    a, b = socket.socketpair()
    a.close()
    with pytest.raises(ConnectionError):
        recv_msg(b)
    b.close()


def test_framing_negative_ids_roundtrip():
    """Canary probes carry stage_index = PING_STAGE (-1) and negative
    request ids — the header must be signed (regression: '>BIQI' raised
    struct.error and killed the dispatcher's watchdog thread)."""
    a, b = socket.socketpair()
    try:
        msg = Message(MSG_DATA, -1, -7, 0, b"")
        t = threading.Thread(target=send_msg, args=(a, msg))
        t.start()
        got = recv_msg(b)
        t.join()
        assert got == msg
    finally:
        a.close()
        b.close()


# -- zero-copy framing (the serving hot path) -------------------------------


def test_raw_unpack_shares_receive_buffer():
    """The zero-copy receive contract: ``unpack`` on the raw codec
    returns an array VIEWING the frame buffer — mutating the buffer's
    payload region must show through the array, and shares_memory must
    agree."""
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    buf = codec_lib.pack(codec_lib.get_codec("none"), x)
    y = codec_lib.unpack(buf)
    np.testing.assert_array_equal(x, y)
    assert np.shares_memory(y, np.frombuffer(buf, dtype=np.uint8))
    buf[-4:] = np.float32(123.5).tobytes()  # poke the last element
    assert y[-1, -1] == 123.5


def test_pack_payload_copy_budget():
    """Framing-layer copy budget, counted not asserted-by-docstring:
    ``pack_frames`` performs ZERO payload copies (scatter-write parts);
    ``pack`` exactly ONE (frame assembly — the old encode-then-concat
    scheme paid two); lossy codecs stay within the same budget (their
    transform output is the payload, not a copy of it)."""
    x = np.random.RandomState(0).standard_normal((32, 256)).astype(
        np.float32
    )
    for name in codec_lib.CODECS:
        c = codec_lib.get_codec(name)
        codec_lib.reset_copy_stats()
        frames = codec_lib.pack_frames(c, x)
        assert codec_lib.copy_stats()["calls"] == 0, name
        payload = codec_lib.frames_nbytes(frames) - len(frames[0])
        codec_lib.reset_copy_stats()
        codec_lib.pack(c, x)
        stats = codec_lib.copy_stats()
        assert stats["calls"] == 1, name
        assert stats["bytes"] <= payload, name
    codec_lib.reset_copy_stats()


def test_pack_into_reuses_pooled_buffer():
    """``pack_into`` grows the caller's pool once, then reuses it: the
    returned views of two same-size packs alias the same bytearray."""
    x = np.arange(100, dtype=np.float32)
    pool = bytearray()
    v1 = codec_lib.pack_into(codec_lib.get_codec("none"), x, pool)
    n1 = len(pool)
    v2 = codec_lib.pack_into(codec_lib.get_codec("none"), x + 1, pool)
    assert len(pool) == n1  # no regrowth for an equal-size frame
    assert v2.obj is pool
    np.testing.assert_array_equal(codec_lib.unpack(v2), x + 1)
    assert v1.nbytes == v2.nbytes


def test_framing_scatter_send_multipart_payload():
    """``send_msg`` accepts a ``pack_frames`` list (header + payload
    views) and the receiver sees one contiguous frame whose ``unpack``
    recovers the array — the end-to-end zero-copy hop: no host-side
    payload concatenation on send, a buffer-viewing array on receive."""
    x = np.random.RandomState(3).standard_normal((16, 128)).astype(
        np.float32
    )
    frames = codec_lib.pack_frames(codec_lib.get_codec("none"), x)
    a, b = socket.socketpair()
    try:
        msg = Message(MSG_DATA, 1, 42, 0, frames)
        t = threading.Thread(target=send_msg, args=(a, msg))
        t.start()
        got = recv_msg(b)
        t.join()
        assert isinstance(got.payload, memoryview)
        y = codec_lib.unpack(got.payload)
        np.testing.assert_array_equal(x, y)
        # int8dev's two payload parts (values + scales) ride the same way
        frames2 = codec_lib.pack_frames(
            codec_lib.get_codec("int8dev"), jnp.asarray(x)
        )
        assert len(frames2) >= 3  # header + >= 2 parts
        t = threading.Thread(
            target=send_msg, args=(a, Message(MSG_DATA, 1, 43, 0, frames2))
        )
        t.start()
        got2 = recv_msg(b)
        t.join()
        y2 = codec_lib.unpack(got2.payload)
        assert y2.shape == x.shape
        np.testing.assert_allclose(
            y2, x, atol=2e-2 * max(1.0, np.max(np.abs(x)))
        )
    finally:
        a.close()
        b.close()


# -- remote worker end-to-end ----------------------------------------------


@pytest.fixture(scope="module")
def remote_worker_proc():
    """A real worker process serving stages over TCP (CPU backend)."""
    port = 17591
    proc = spawn_worker_proc("--port", str(port), "--heartbeat", "0.1")
    yield "127.0.0.1", port
    proc.terminate()
    proc.wait(timeout=10)


def test_remote_worker_full_pipeline(remote_worker_proc, devices):
    """Dispatcher drives a mixed pool: 2 in-process workers + 1 remote
    process, ViT-tiny split in 2 stages, int8 activation codec across the
    host boundary."""
    from adapt_tpu.comm.remote import RemoteWorkerProxy
    from adapt_tpu.config import FaultConfig, ServeConfig
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_tiny

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    plan = partition(g, ["encoder_block_1"])
    y_ref = np.asarray(g.apply(variables, x))

    cfg = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=1.0,
            heartbeat_s=0.2,
            task_deadline_s=30.0,
            watchdog_period_s=0.1,
            startup_wait_s=10.0,
            configure_timeout_s=60.0,
        )
    )
    disp = Dispatcher(plan, variables, config=cfg)
    disp.spawn_workers(devices[:2])
    proxy = RemoteWorkerProxy(
        "remote-0",
        remote_worker_proc,
        disp.registry,
        disp.result_queue,
        model_config={
            "model": "vit_tiny",
            "num_classes": 10,
            "cuts": ["encoder_block_1"],
            "input_shape": [2, 32, 32, 3],
        },
        codec_name="int8",
        fault=cfg.fault,
    )
    disp.attach_worker(proxy)
    disp.start()
    try:
        proxy_started = proxy.start() if proxy._sock is None else proxy
        assert "remote-0" in disp.registry.alive()
        # Force the remote to own stage 1: configure it there explicitly.
        proxy_started.configure(1, None, plan.extract_variables(variables)[1])
        assert proxy_started.is_configured(1)
        # Run requests; results must match within int8 quantization error.
        outs = disp.serve_stream([x] * 4, timeout_per_request=60.0)
        for y in outs:
            assert np.max(np.abs(np.asarray(y) - y_ref)) < 0.3
        # Kill the remote (crash): lease must lapse and serving continue on
        # local workers only.
        proxy_started.kill("crash")
        deadline = time.monotonic() + 5.0
        while "remote-0" in disp.registry.alive():
            assert time.monotonic() < deadline, "remote lease never expired"
            time.sleep(0.05)
        outs2 = disp.serve_stream([x] * 2, timeout_per_request=60.0)
        assert len(outs2) == 2
    finally:
        disp.shutdown()


def test_remote_probe_roundtrip_and_hang_swallow():
    """The dispatcher's canary probes must round-trip the remote serve
    loop (not just the transport ping thread): a healthy server answers a
    PING_STAGE task; a hung server swallows it so the probe deadline can
    fire. Regression for probes crashing on the remote submit path."""
    import queue as queue_mod

    from adapt_tpu.comm.remote import RemoteWorkerProxy
    from adapt_tpu.config import FaultConfig
    from adapt_tpu.control.registry import WorkerRegistry
    from adapt_tpu.control.worker import PING_STAGE, Task

    port = 17593
    proc = spawn_worker_proc("--port", str(port), "--heartbeat", "0.1")
    registry = WorkerRegistry(default_ttl_s=2.0).start()
    results: "queue_mod.Queue" = queue_mod.Queue()
    proxy = RemoteWorkerProxy(
        "remote-probe",
        ("127.0.0.1", port),
        registry,
        results,
        model_config={},
        fault=FaultConfig(startup_wait_s=10.0),
    )
    try:
        proxy.start()
        probe = Task(
            request_id=-5, stage_index=PING_STAGE, attempt=0, payload=None
        )
        proxy.submit(probe)
        ans = results.get(timeout=5.0)
        assert ans.stage_index == PING_STAGE
        assert ans.request_id == -5
        assert ans.worker_id == "remote-probe"
        assert ans.error is None
        # Probes must not count as in-flight work on the proxy.
        assert proxy.queue_depth == 0
        proxy.kill("hang")
        time.sleep(0.2)
        proxy.submit(
            Task(request_id=-6, stage_index=PING_STAGE, attempt=0, payload=None)
        )
        with pytest.raises(queue_mod.Empty):
            results.get(timeout=1.5)
    finally:
        proxy.stop()
        registry.stop()
        proc.terminate()
        proc.wait(timeout=10)


# -- chain forwarding (direct worker→worker data plane) ----------------------


def test_chain_forwarding_bypasses_hub(devices):
    """3 remote workers in chain mode: every intermediate activation hops
    worker→worker (reference Gen-1 topology, ``src/node.py:163-179``);
    the hub's links deliver ONLY the tail's final results, and outputs
    equal the unpartitioned forward bit-for-bit (codec 'none')."""
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_block_cuts, vit_tiny

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    cuts = vit_block_cuts(4, 3)
    plan = partition(g, cuts)
    y_ref = np.asarray(g.apply(variables, x))
    cfg = chain_cfg()
    disp = Dispatcher(plan, variables, config=cfg)
    procs, proxies = chain_pool(disp, cfg, cuts, [17621, 17622, 17623])
    try:
        disp.start()
        for pr in proxies:
            pr.start()
        order = disp.setup_chain([pr.worker_id for pr in proxies])
        assert order == ["chain-0", "chain-1", "chain-2"]
        outs = disp.serve_stream([x] * 6, timeout_per_request=120.0)
        for y in outs:
            np.testing.assert_allclose(
                np.asarray(y), y_ref, rtol=1e-5, atol=1e-5
            )
        # The hub never touched an intermediate activation: the head and
        # mid proxies delivered ZERO result frames; every result came in
        # on the tail's link.
        assert proxies[0].results_received == 0
        assert proxies[1].results_received == 0
        assert proxies[2].results_received == 6
    finally:
        disp.shutdown()
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def test_chain_failure_falls_back_to_hub_exactly_once(devices):
    """Kill the MID-chain worker: the chain disables itself and serving
    continues through the late-binding hub path on the survivors + local
    workers — every request completes exactly once with the right
    answer."""
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_block_cuts, vit_tiny

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    cuts = vit_block_cuts(4, 3)
    plan = partition(g, cuts)
    y_ref = np.asarray(g.apply(variables, x))
    cfg = chain_cfg()
    disp = Dispatcher(plan, variables, config=cfg)
    # Local fallback capacity for after the kill.
    disp.spawn_workers(devices[:2])
    procs, proxies = chain_pool(disp, cfg, cuts, [17631, 17632, 17633])
    try:
        disp.start()
        for pr in proxies:
            pr.start()
        disp.setup_chain([pr.worker_id for pr in proxies])
        outs = disp.serve_stream([x] * 2, timeout_per_request=120.0)
        for y in outs:
            np.testing.assert_allclose(
                np.asarray(y), y_ref, rtol=1e-5, atol=1e-5
            )
        proxies[1].kill("crash")
        # Membership notices (link drop -> deregister) and the chain
        # disables itself.
        deadline = time.monotonic() + 10.0
        while disp._chain is not None:
            assert time.monotonic() < deadline, "chain never disabled"
            time.sleep(0.05)
        outs2 = disp.serve_stream([x] * 4, timeout_per_request=120.0)
        for y in outs2:
            np.testing.assert_allclose(
                np.asarray(y), y_ref, rtol=1e-5, atol=1e-5
            )
    finally:
        disp.shutdown()
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def test_chain_rejects_in_process_workers(devices):
    """Chaining is a cross-host topology; in-process workers share the
    hub's memory, so setup_chain must refuse them loudly."""
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_tiny

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    plan = partition(g, ["encoder_block_1"])
    disp = Dispatcher(plan, variables)
    workers = disp.spawn_workers(devices[:2])
    disp.start()
    try:
        with pytest.raises(TypeError, match="cannot chain"):
            disp.setup_chain([w.worker_id for w in workers])
    finally:
        disp.shutdown()


def test_chain_forwarding_composes_with_codec(devices):
    """Chain hops carry codec-packed activations (frames are
    self-describing, so each hop unpacks whatever its upstream packed):
    int8-quantized activations over a 3-hop chain must still produce
    outputs within quantization tolerance of the full model."""
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_block_cuts, vit_tiny

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    cuts = vit_block_cuts(4, 3)
    plan = partition(g, cuts)
    y_ref = np.asarray(g.apply(variables, x))
    cfg = chain_cfg()
    disp = Dispatcher(plan, variables, config=cfg)
    procs, proxies = chain_pool(
        disp, cfg, cuts, [17645, 17646, 17647],
        codec_name="int8", prefix="cchain",
    )
    try:
        disp.start()
        for pr in proxies:
            pr.start()
        disp.setup_chain([pr.worker_id for pr in proxies])
        outs = disp.serve_stream([x] * 4, timeout_per_request=120.0)
        for y in outs:
            assert np.max(np.abs(np.asarray(y) - y_ref)) < 0.3
        assert proxies[0].results_received == 0
        assert proxies[2].results_received == 4
    finally:
        disp.shutdown()
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def test_chain_kill_mid_burst_exactly_once(devices):
    """Kill the TAIL chain worker while a burst is in flight: chain
    entries in every state (queued at head, mid-hop, awaiting tail) must
    replay end-to-end through the hub path — exactly once, right
    answers, no hangs. This is the riskiest chain path: whole-request
    replay racing live traffic."""
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_block_cuts, vit_tiny

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    cuts = vit_block_cuts(4, 3)
    plan = partition(g, cuts)
    y_ref = np.asarray(g.apply(variables, x))
    cfg = chain_cfg()
    disp = Dispatcher(plan, variables, config=cfg)
    # Local fallback pool so replays have somewhere to land even while
    # remote membership churns.
    disp.spawn_workers(devices[:3])
    procs, proxies = chain_pool(disp, cfg, cuts, [17641, 17642, 17643])
    try:
        disp.start()
        for pr in proxies:
            pr.start()
        disp.setup_chain([pr.worker_id for pr in proxies])
        disp.serve_stream([x] * 2, timeout_per_request=120.0)  # warm chain
        futures = [disp.submit(x) for _ in range(10)]
        proxies[2].kill("crash")  # tail dies with the burst in flight
        outs = [f.result(180.0) for f in futures]
        for y in outs:
            np.testing.assert_allclose(
                np.asarray(y), y_ref, rtol=1e-5, atol=1e-5
            )
        assert disp._chain is None  # the failure disabled the chain
        # Exactly-once: every submitted future completed with a value
        # (no double-complete is possible through PipelineFuture, and
        # none errored).
        assert all(f._error is None for f in futures)
    finally:
        disp.shutdown()
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


# -- architecture-by-value ---------------------------------------------------


def test_registry_less_worker_serves_partitioned_resnet(devices):
    """A worker started with --no-registry (bare image: framework, no
    model zoo) serves a partitioned ResNet-50 configured entirely BY
    VALUE — the serialized LayerGraph rides in MSG_CONFIG (reference
    ``model.to_json()`` → ``model_from_json``, ``src/dispatcher.py:235``
    / ``src/node.py:40-45``). A by-NAME configure to the same worker must
    fail loudly."""
    from adapt_tpu.comm.remote import RemoteWorkerProxy
    from adapt_tpu.config import FaultConfig, ServeConfig
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import graph_to_spec, partition
    from adapt_tpu.models.resnet import RESNET50_3STAGE_CUTS, resnet50

    g = resnet50(num_classes=10)
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    cuts = list(RESNET50_3STAGE_CUTS)
    plan = partition(g, cuts)
    y_ref = np.asarray(g.apply(variables, x))

    port = 17651
    proc = spawn_worker_proc(
        "--port", str(port), "--heartbeat", "0.2", "--no-registry"
    )
    cfg = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=2.0,
            heartbeat_s=0.2,
            task_deadline_s=60.0,
            watchdog_period_s=0.5,
            startup_wait_s=15.0,
            configure_timeout_s=120.0,
        )
    )
    disp = Dispatcher(plan, variables, config=cfg)
    proxy = RemoteWorkerProxy(
        "by-value-0",
        ("127.0.0.1", port),
        disp.registry,
        disp.result_queue,
        model_config={
            "graph_spec": graph_to_spec(g),
            "cuts": cuts,
            "input_shape": [2, 64, 64, 3],
        },
        fault=cfg.fault,
    )
    disp.attach_worker(proxy)
    disp.start()
    try:
        proxy.start()
        # Configure ALL stages on the remote: every result the hub gets
        # came from spec-rebuilt stages, none from local registry code.
        for i in range(plan.num_stages):
            proxy.configure(i, None, plan.extract_variables(variables)[i])
        outs = disp.serve_stream([x] * 3, timeout_per_request=120.0)
        for y in outs:
            np.testing.assert_allclose(
                np.asarray(y), y_ref, rtol=1e-5, atol=1e-5
            )
        assert proxy.results_received >= 3 * plan.num_stages
        # By-name configure against the bare worker: loud refusal.
        proxy._model_config = {
            "model": "resnet50",
            "num_classes": 10,
            "cuts": cuts,
            "input_shape": [2, 64, 64, 3],
        }
        with pytest.raises(RuntimeError, match="architecture-by-value"):
            proxy.configure(0, None, plan.extract_variables(variables)[0])
    finally:
        disp.shutdown()
        proc.terminate()
        proc.wait(timeout=10)


# -- data-plane hardening ----------------------------------------------------


def test_concurrent_configures_do_not_clobber(devices):
    """Two configure() calls racing on the SAME proxy (the dispatcher's
    recovery path can reach this from two forward threads) must each get
    their own ACK — generation-keyed handshake state, not a shared
    per-stage dict."""
    import queue as queue_mod

    from adapt_tpu.comm.remote import RemoteWorkerProxy
    from adapt_tpu.config import FaultConfig
    from adapt_tpu.control.registry import WorkerRegistry
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_tiny

    port = 17597
    proc = spawn_worker_proc("--port", str(port), "--heartbeat", "0.1")
    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(1), x)
    plan = partition(g, ["encoder_block_1"])
    stage_vars = plan.extract_variables(variables)

    registry = WorkerRegistry(default_ttl_s=2.0).start()
    results: "queue_mod.Queue" = queue_mod.Queue()
    proxy = RemoteWorkerProxy(
        "remote-cc",
        ("127.0.0.1", port),
        registry,
        results,
        model_config={
            "model": "vit_tiny",
            "num_classes": 10,
            "cuts": ["encoder_block_1"],
            "input_shape": [2, 32, 32, 3],
        },
        fault=FaultConfig(startup_wait_s=10.0, configure_timeout_s=60.0),
    )
    try:
        proxy.start()
        errors = []

        def cfg(stage):
            try:
                proxy.configure(stage, None, stage_vars[stage])
            except Exception as e:  # noqa: BLE001
                errors.append((stage, e))

        # Same stage twice concurrently + the other stage: all must land.
        threads = [
            threading.Thread(target=cfg, args=(s,)) for s in (1, 1, 0)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90.0)
        assert not errors, errors
        assert proxy.is_configured(0) and proxy.is_configured(1)
    finally:
        proxy.stop()
        registry.stop()
        proc.terminate()
        proc.wait(timeout=10)


def test_stalled_peer_send_times_out_not_wedges():
    """A peer that stops draining its socket (hung process, full TCP
    buffers) must not wedge the sender forever: the bounded send raises
    within ~send_timeout_s and the proxy marks its link dead so the
    scheduler routes around it."""
    import queue as queue_mod

    from adapt_tpu.comm.remote import RemoteWorkerProxy
    from adapt_tpu.config import FaultConfig
    from adapt_tpu.control.registry import WorkerRegistry
    from adapt_tpu.control.worker import Task, WorkerState

    # A server that accepts and then never reads: sendall must eventually
    # block once kernel buffers fill.
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    accepted = []

    def accept_only():
        conn, _ = srv.accept()
        accepted.append(conn)  # keep alive, never read

    t = threading.Thread(target=accept_only, daemon=True)
    t.start()

    registry = WorkerRegistry(default_ttl_s=5.0).start()
    results: "queue_mod.Queue" = queue_mod.Queue()
    proxy = RemoteWorkerProxy(
        "remote-stall",
        ("127.0.0.1", port),
        registry,
        results,
        model_config={},
        fault=FaultConfig(startup_wait_s=5.0, send_timeout_s=1.0),
    )
    try:
        proxy.start()
        proxy._sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
        big = np.zeros((4 << 20,), np.uint8)  # 4 MB >> buffer space
        start = time.monotonic()
        with pytest.raises((ConnectionError, TimeoutError)):
            for _ in range(8):  # first sends may fit in buffers
                proxy.submit(
                    Task(request_id=1, stage_index=0, attempt=0, payload=big)
                )
        elapsed = time.monotonic() - start
        assert elapsed < 10.0, f"send wedged for {elapsed:.1f}s"
        # The link is condemned: state DEAD, membership eviction immediate.
        assert proxy.state is WorkerState.DEAD
        assert "remote-stall" not in registry.alive()
    finally:
        proxy.stop()
        registry.stop()
        for c in accepted:
            c.close()
        srv.close()


# -- worker-initiated join (the pool can GROW) -------------------------------


def test_worker_joins_running_pipeline_via_gateway(devices):
    """The reference's defining adaptive capability: a FRESH worker
    registers itself with a RUNNING pipeline (src/node_state.py:17-20) and
    subsequently serves stages. Here: mid-stream, a new worker process
    dials the WorkerGateway; after the local workers are crashed, requests
    keep completing — only the joined worker can be serving them."""
    from adapt_tpu.comm.remote import WorkerGateway
    from adapt_tpu.config import CodecConfig, FaultConfig, ServeConfig
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_tiny

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    plan = partition(g, ["encoder_block_1"])
    y_ref = np.asarray(g.apply(variables, x))

    cfg = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=1.0,
            heartbeat_s=0.2,
            task_deadline_s=30.0,
            watchdog_period_s=0.1,
            startup_wait_s=10.0,
            configure_timeout_s=60.0,
        ),
        codec=CodecConfig(name="int8", weights="lz"),
    )
    disp = Dispatcher(plan, variables, config=cfg)
    local = disp.spawn_workers(devices[:2])
    gateway = WorkerGateway(
        disp,
        model_config={
            "model": "vit_tiny",
            "num_classes": 10,
            "cuts": ["encoder_block_1"],
            "input_shape": [2, 32, 32, 3],
        },
    )
    proc = None
    procs2: list = []
    try:
        disp.start()
        gateway.start()
        # Pipeline is live and serving before the newcomer exists.
        outs = disp.serve_stream([x] * 3, timeout_per_request=60.0)
        assert all(
            np.max(np.abs(np.asarray(y) - y_ref)) < 0.3 for y in outs
        )

        proc = spawn_worker_proc(
            "--connect", f"127.0.0.1:{gateway.port}",
            "--worker-id", "joiner-0", "--heartbeat", "0.1",
        )
        deadline = time.monotonic() + 30.0
        while "joiner-0" not in disp.registry.alive():
            assert time.monotonic() < deadline, "worker never joined"
            time.sleep(0.05)
        # Pool grew mid-stream; keep serving through the join.
        outs = disp.serve_stream([x] * 3, timeout_per_request=60.0)
        assert all(
            np.max(np.abs(np.asarray(y) - y_ref)) < 0.3 for y in outs
        )
        # A SECOND worker must also be able to join while a device-less
        # remote proxy is already attached (regression: the join-watch
        # prewarm read .device off every worker and crashed the gateway
        # accept loop, capping the pool at one remote).
        proc2 = spawn_worker_proc(
            "--connect", f"127.0.0.1:{gateway.port}",
            "--worker-id", "joiner-1", "--heartbeat", "0.1",
        )
        procs2.append(proc2)
        deadline = time.monotonic() + 30.0
        while "joiner-1" not in disp.registry.alive():
            assert time.monotonic() < deadline, "second worker never joined"
            time.sleep(0.05)
        # Crash every local worker: only the joined remotes can serve now.
        for w in local:
            w.kill("crash")
        deadline = time.monotonic() + 10.0
        while any(w.worker_id in disp.registry.alive() for w in local):
            assert time.monotonic() < deadline, "local leases never lapsed"
            time.sleep(0.05)
        outs = disp.serve_stream([x] * 2, timeout_per_request=90.0)
        for y in outs:
            assert np.max(np.abs(np.asarray(y) - y_ref)) < 0.3
        assert "joiner-0" in disp.registry.alive()
    finally:
        for p in [proc, *procs2]:
            if p is not None:
                p.terminate()
                p.wait(timeout=10)
        gateway.stop()
        disp.shutdown()


def test_serving_pipeline_elastic_gateway(devices):
    """One-constructor elastic serving: ServingPipeline(gateway_model_config=...)
    opens the join gateway; a worker process dials it and serves."""
    from adapt_tpu.config import FaultConfig, ServeConfig
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_tiny
    from adapt_tpu.runtime import ServingPipeline

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    plan = partition(g, ["encoder_block_1"])
    y_ref = np.asarray(g.apply(variables, x))

    pipe = ServingPipeline(
        plan,
        variables,
        devices=devices[:2],
        config=ServeConfig(
            fault=FaultConfig(
                lease_ttl_s=1.0, heartbeat_s=0.2, startup_wait_s=10.0
            )
        ),
        gateway_model_config={
            "model": "vit_tiny",
            "num_classes": 10,
            "cuts": ["encoder_block_1"],
            "input_shape": [2, 32, 32, 3],
        },
    )
    proc = None
    try:
        pipe.start()
        assert pipe.gateway_port
        proc = spawn_worker_proc(
            "--connect", f"127.0.0.1:{pipe.gateway_port}",
            "--worker-id", "elastic-0", "--heartbeat", "0.1",
        )
        deadline = time.monotonic() + 30.0
        while "elastic-0" not in pipe.registry.alive():
            assert time.monotonic() < deadline, "joiner never registered"
            time.sleep(0.05)
        outs = pipe.stream([x] * 2, timeout_per_request=60.0)
        for y in outs:
            assert np.max(np.abs(np.asarray(y) - y_ref)) < 0.3
    finally:
        if proc is not None:
            proc.terminate()
            proc.wait(timeout=10)
        pipe.shutdown()


def _raw_hello(port: int, worker_id: str, secret: str | None = None):
    """Dial a gateway and send a bare HELLO; returns ("ack", None) on
    acceptance, ("rejected", reason) when the gateway closes the link
    before saying anything. Acceptance = ANY message arrives: the
    dispatcher's join-watch prewarm can put a MSG_CONFIG on the wire
    before the gateway's HELLO_ACK (they race by design)."""
    import json as _json

    from adapt_tpu.comm.remote import MSG_HELLO

    info = {"worker_id": worker_id}
    if secret is not None:
        info["secret"] = secret
    conn = socket.create_connection(("127.0.0.1", port), timeout=5.0)
    send_msg(conn, Message(MSG_HELLO, 0, 0, 0, _json.dumps(info).encode()))
    conn.settimeout(5.0)
    try:
        recv_msg(conn, retry_on_timeout=False)
    except Exception as e:  # noqa: BLE001 — closed link == rejection
        conn.close()
        return "rejected", str(e)
    # Accepted: hand the OPEN socket back — closing it would make the
    # gateway proxy deregister the lease (link-drop eviction) before the
    # caller can observe it.
    return "ack", conn


def test_gateway_rejects_duplicate_live_worker_id_and_bad_secret(devices):
    """Gateway hardening (above reference parity — the reference has no
    auth anywhere, SURVEY.md §2.8): a joiner announcing a LIVE worker's
    id is rejected (it would race that worker's lease and interleave two
    links under one identity), and when the gateway carries a secret, a
    join without the matching one is rejected (constant-time compare)."""
    from adapt_tpu.comm.remote import WorkerGateway
    from adapt_tpu.config import FaultConfig, ServeConfig
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_tiny

    g = vit_tiny()
    x = jnp.ones((1, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    plan = partition(g, ["encoder_block_1"])
    cfg = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=2.0, heartbeat_s=0.2, startup_wait_s=10.0
        )
    )
    disp = Dispatcher(plan, variables, config=cfg)
    local = disp.spawn_workers(devices[:2])
    gateway = WorkerGateway(
        disp,
        model_config={"model": "vit_tiny", "num_classes": 10,
                      "cuts": ["encoder_block_1"],
                      "input_shape": [1, 32, 32, 3]},
        secret="open-sesame",
    )
    try:
        disp.start()
        gateway.start()
        live_id = local[0].worker_id
        assert live_id in disp.registry.alive()

        # No secret / wrong secret: closed before any attach.
        assert _raw_hello(gateway.port, "mallory")[0] == "rejected"
        assert (
            _raw_hello(gateway.port, "mallory", secret="guess")[0]
            == "rejected"
        )
        assert "mallory" not in disp.registry.alive()

        # Right secret but a LIVE worker's id: rejected, live lease
        # untouched.
        status, _ = _raw_hello(gateway.port, live_id, secret="open-sesame")
        assert status == "rejected"
        assert live_id in disp.registry.alive()

        # Right secret, fresh id: accepted (message flows + lease
        # registered while the link stays open).
        status, conn = _raw_hello(
            gateway.port, "joiner-x", secret="open-sesame"
        )
        assert status == "ack"
        try:
            deadline = time.monotonic() + 10.0
            while "joiner-x" not in disp.registry.alive():
                assert time.monotonic() < deadline
                time.sleep(0.02)
        finally:
            conn.close()
    finally:
        gateway.stop()
        disp.shutdown()
