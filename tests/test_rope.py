"""Rotary position embeddings across every decode path.

RoPE's contract here: q/k rotate by LOGICAL position in every schedule
(full forward, prefill, cached decode, verify_chunk, paged chunk
prefill), the cache stores post-rotation K, and — because logical
positions are used, not buffer positions — ragged rows stay
bitwise-equal to their solo runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.models.transformer_lm import (
    apply_rope,
    generate,
    logits_full,
    transformer_lm,
)
from adapt_tpu.runtime.continuous import ContinuousBatcher


@pytest.fixture(scope="module")
def rlm_setup():
    lm = transformer_lm(
        43, 32, 2, 4, 64, max_len=96, kv_heads=2, pos="rope",
        name="rope_lm",
    )
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


def test_rope_is_relative():
    """The defining property: shifting q AND k positions by a constant
    leaves attention scores unchanged (up to fp)."""
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (1, 2, 8, 16))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (1, 2, 8, 16))
    pos = jnp.arange(8)
    s0 = jnp.einsum(
        "bhqd,bhkd->bhqk", apply_rope(q, pos), apply_rope(k, pos)
    )
    s7 = jnp.einsum(
        "bhqd,bhkd->bhqk",
        apply_rope(q, pos + 37),
        apply_rope(k, pos + 37),
    )
    np.testing.assert_allclose(
        np.asarray(s0), np.asarray(s7), rtol=2e-4, atol=2e-4
    )


def test_rope_drops_pos_table(rlm_setup):
    lm, variables = rlm_setup
    assert "pos_embed" not in variables["embed"]["params"]


def test_rope_cached_decode_matches_full_forward(rlm_setup):
    lm, variables = rlm_setup
    prompt = jax.random.randint(
        jax.random.PRNGKey(2), (2, 12), 0, 43, jnp.int32
    )
    steps = 20
    got = np.asarray(generate(lm, variables, prompt, steps))
    ids = prompt
    for _ in range(steps):
        nxt = jnp.argmax(logits_full(lm, variables, ids)[:, -1], -1)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.asarray(ids)[:, 12:])


def test_rope_ragged_rows_equal_solo_bitwise(rlm_setup):
    """Logical-position rotation: a left-padded row's angles equal its
    solo run's angles exactly, so even SAMPLED streams match for row 0
    and greedy matches for every row."""
    lm, variables = rlm_setup
    prompt = jax.random.randint(
        jax.random.PRNGKey(3), (3, 14), 0, 43, jnp.int32
    )
    lengths = jnp.asarray([14, 6, 9], jnp.int32)
    out = np.asarray(
        generate(lm, variables, prompt, 15, prompt_lengths=lengths)
    )
    for r in range(3):
        solo = np.asarray(
            generate(lm, variables, prompt[r:r + 1, : int(lengths[r])], 15)
        )[0]
        np.testing.assert_array_equal(out[r], solo, err_msg=f"row {r}")


def test_rope_composes_with_window_and_paged_serving(rlm_setup):
    """RoPE + sliding window + paged batcher + prefix cache + chunked
    prefill in one model: streams equal solo generate()."""
    lm = transformer_lm(
        43, 32, 2, 4, 64, max_len=128, kv_heads=2, pos="rope", window=20,
        name="rope_win_lm",
    )
    variables = lm.graph.init(
        jax.random.PRNGKey(4), jnp.zeros((1, 4), jnp.int32)
    )
    rng = np.random.RandomState(5)
    system = rng.randint(0, 43, size=32).astype(np.int32)
    p1 = np.concatenate([system, rng.randint(0, 43, size=6).astype(np.int32)])
    p2 = np.concatenate([system, rng.randint(0, 43, size=30).astype(np.int32)])
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged", page_size=16,
        prefill_chunk=16,
    )
    r1 = bat.submit(p1, 30)
    bat.tick()
    r2 = bat.submit(p2, 12)  # prefix hit + chunked suffix
    out = bat.run()
    np.testing.assert_array_equal(
        out[r1],
        np.asarray(generate(lm, variables, jnp.asarray(p1)[None], 30))[0],
    )
    np.testing.assert_array_equal(
        out[r2],
        np.asarray(generate(lm, variables, jnp.asarray(p2)[None], 12))[0],
    )


def test_rope_speculative_lossless(rlm_setup):
    from adapt_tpu.models.speculative import speculative_generate

    lm, variables = rlm_setup
    prompt = jax.random.randint(
        jax.random.PRNGKey(6), (1, 9), 0, 43, jnp.int32
    )
    want = np.asarray(generate(lm, variables, prompt, 14))
    got = speculative_generate(
        lm, variables, prompt, 14, lm, variables, draft_k=4
    )
    np.testing.assert_array_equal(np.asarray(got), want)


def test_rope_validation():
    with pytest.raises(ValueError, match="pos="):
        transformer_lm(43, 32, 2, 4, 64, pos="alibi")
