"""HTTP metrics exporter: scrape paths, formats, and teardown."""

import json
import urllib.error
import urllib.request

from adapt_tpu.utils.exporter import serve_metrics
from adapt_tpu.utils.metrics import MetricsRegistry


def test_metrics_exporter_serves_prom_and_json():
    reg = MetricsRegistry()
    reg.inc("dispatcher.completed", 5)
    reg.set_gauge("continuous.active_slots", 3)
    reg.observe("stage.latency_s", 0.1)
    reg.observe("stage.latency_s", 0.3)
    server = serve_metrics(port=0, registry=reg)
    try:
        port = server.server_address[1]

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return r.read().decode(), r.headers.get("Content-Type")

        text, ctype = get("/metrics")
        assert "text/plain" in ctype
        assert "adapt_dispatcher_completed_total 5" in text
        assert "adapt_continuous_active_slots 3" in text
        assert "adapt_stage_latency_s_count 2" in text
        # _sum is the exact running total, not mean*count.
        assert "adapt_stage_latency_s_sum 0.4" in text
        assert "adapt_stage_latency_s_p50" in text

        js, ctype = get("/metrics.json")
        snap = json.loads(js)
        assert snap["counters"]["dispatcher.completed"] == 5
        assert snap["histograms"]["stage.latency_s"]["sum"] == 0.4
        assert "application/json" in ctype

        ok, _ = get("/healthz")
        assert json.loads(ok)["ok"] is True

        try:
            get("/nope")
            raise AssertionError("404 expected")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.shutdown()
        server.server_close()  # shutdown alone leaks the listening fd
