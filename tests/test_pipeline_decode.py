"""Pipelined KV-cache generation must be token-for-token identical to
single-program ``generate`` — greedy, sampled, ragged, EOS-padded, and
int8-cached. The pipeline is a different *schedule* over the same weights
(rank-local block slices + device-resident caches + a ppermute token
ring), so any divergence is a scheduling bug, not a modeling choice."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from adapt_tpu.models.transformer_lm import generate, lm_tiny
from adapt_tpu.parallel.pipeline_decode import pipelined_generate


@pytest.fixture(scope="module")
def pp4(devices):
    return Mesh(np.array(devices[:4]), ("pp",))


@pytest.fixture(scope="module")
def lm_and_vars():
    lm = lm_tiny(vocab=61, max_len=32)  # depth 4 -> 1 block per rank
    prompt = jax.random.randint(jax.random.PRNGKey(0), (8, 5), 0, 61)
    variables = lm.graph.init(jax.random.PRNGKey(1), prompt)
    return lm, variables, prompt


def test_greedy_matches_generate(pp4, lm_and_vars):
    lm, variables, prompt = lm_and_vars
    want = np.asarray(generate(lm, variables, prompt, 7))
    got = np.asarray(
        pipelined_generate(lm, variables, prompt, 7, pp4)
    )
    np.testing.assert_array_equal(got, want)


def test_sampled_matches_generate(pp4, lm_and_vars):
    """Per-row sampling keys make microbatch slices draw exactly what the
    full batch draws — so even tempered/top-k sampling matches."""
    lm, variables, prompt = lm_and_vars
    kw = dict(temperature=0.9, top_k=7, rng=jax.random.PRNGKey(3))
    want = np.asarray(generate(lm, variables, prompt, 6, **kw))
    got = np.asarray(
        pipelined_generate(lm, variables, prompt, 6, pp4, **kw)
    )
    np.testing.assert_array_equal(got, want)


def test_eos_matches_generate(pp4, lm_and_vars):
    lm, variables, prompt = lm_and_vars
    greedy = np.asarray(generate(lm, variables, prompt, 6))
    eos = int(greedy[0, 0])  # forces at least one row to finish early
    want = np.asarray(generate(lm, variables, prompt, 6, eos_id=eos))
    got = np.asarray(
        pipelined_generate(lm, variables, prompt, 6, pp4, eos_id=eos)
    )
    np.testing.assert_array_equal(got, want)
    assert (got[0] == eos).all()


def test_ragged_matches_generate(pp4):
    lm = lm_tiny(vocab=47, max_len=32)
    lens = [3, 6, 2, 5, 4, 6, 1, 3]
    s0 = max(lens)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (8, s0), 0, 47)
    lengths = jnp.asarray(lens)
    variables = lm.graph.init(jax.random.PRNGKey(6), prompt)
    want = np.asarray(
        generate(lm, variables, prompt, 5, prompt_lengths=lengths)
    )
    got = np.asarray(
        pipelined_generate(
            lm, variables, prompt, 5, pp4, prompt_lengths=lengths
        )
    )
    np.testing.assert_array_equal(got, want)


def test_int8_cache_matches_generate(pp4, lm_and_vars):
    lm, variables, prompt = lm_and_vars
    want = np.asarray(
        generate(lm, variables, prompt, 6, kv_cache_dtype="int8")
    )
    got = np.asarray(
        pipelined_generate(
            lm, variables, prompt, 6, pp4, kv_cache_dtype="int8"
        )
    )
    np.testing.assert_array_equal(got, want)


def test_single_step(pp4, lm_and_vars):
    """steps=1 is prefill-only — no decode ring at all."""
    lm, variables, prompt = lm_and_vars
    want = np.asarray(generate(lm, variables, prompt, 1))
    got = np.asarray(pipelined_generate(lm, variables, prompt, 1, pp4))
    np.testing.assert_array_equal(got, want)


def test_two_ranks_two_blocks_each(devices, lm_and_vars):
    """Pipeline size 2: each rank holds 2 of the 4 blocks."""
    lm, variables, prompt = lm_and_vars
    mesh = Mesh(np.array(devices[:2]), ("pp",))
    want = np.asarray(generate(lm, variables, prompt, 5))
    got = np.asarray(pipelined_generate(lm, variables, prompt, 5, mesh))
    np.testing.assert_array_equal(got, want)


def test_shard_for_pipeline_places_blocks_per_rank(pp4, lm_and_vars):
    """The capacity contract: each rank's devices hold only their own
    L/P block slice (leading dim sharded over pp), embed/head replicated
    — and a pre-placed PipelinedVariables generates identically."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from adapt_tpu.parallel.pipeline_decode import shard_for_pipeline

    lm, variables, prompt = lm_and_vars
    placed = shard_for_pipeline(lm, variables, pp4)
    for leaf in jax.tree.leaves(placed.stacked):
        assert leaf.sharding == NamedSharding(pp4, P("pp")), leaf.sharding
        # Per-device shard covers 1/P of the blocks, not all of them.
        shard_shapes = {s.data.shape for s in leaf.addressable_shards}
        assert shard_shapes == {(lm.depth // 4, *leaf.shape[1:])}
    for leaf in jax.tree.leaves(placed.embed):
        assert leaf.sharding == NamedSharding(pp4, P())
    want = np.asarray(generate(lm, variables, prompt, 5))
    got = np.asarray(pipelined_generate(lm, placed, prompt, 5, pp4))
    np.testing.assert_array_equal(got, want)


def test_rejects_bad_divisibility(pp4, lm_and_vars):
    lm, variables, prompt = lm_and_vars
    with pytest.raises(ValueError, match="batch"):
        pipelined_generate(lm, variables, prompt[:6], 4, pp4)
    lm3 = lm_tiny(vocab=61, max_len=32)
    object.__setattr__(lm3, "depth", 3)
    with pytest.raises(ValueError, match="depth"):
        pipelined_generate(lm3, variables, prompt, 4, pp4)


def test_top_p_matches_generate(pp4, lm_and_vars):
    lm, variables, prompt = lm_and_vars
    kw = dict(temperature=1.0, top_p=0.65, rng=jax.random.PRNGKey(41))
    want = np.asarray(generate(lm, variables, prompt, 5, **kw))
    got = np.asarray(
        pipelined_generate(lm, variables, prompt, 5, pp4, **kw)
    )
    np.testing.assert_array_equal(got, want)


def test_dp_pp_composition_matches_generate(devices):
    """2-D mesh: batch rows shard over dp while blocks + caches shard
    over pp. Sampling uses GLOBAL row indices, so the dp x pp program
    still emits exactly the single-program stream — greedy and sampled,
    dense and ragged."""
    lm = lm_tiny(vocab=71, max_len=32)
    mesh = Mesh(np.array(devices[:8]).reshape(2, 4), ("dp", "pp"))
    prompt = jax.random.randint(jax.random.PRNGKey(50), (16, 5), 0, 71)
    variables = lm.graph.init(jax.random.PRNGKey(51), prompt)

    want = np.asarray(generate(lm, variables, prompt, 5))
    got = np.asarray(
        pipelined_generate(lm, variables, prompt, 5, mesh, dp_axis="dp")
    )
    np.testing.assert_array_equal(got, want)

    kw = dict(temperature=0.9, top_k=11, rng=jax.random.PRNGKey(52))
    want_s = np.asarray(generate(lm, variables, prompt, 4, **kw))
    got_s = np.asarray(
        pipelined_generate(
            lm, variables, prompt, 4, mesh, dp_axis="dp", **kw
        )
    )
    np.testing.assert_array_equal(got_s, want_s)

    lens = jnp.asarray([2, 5, 3, 4] * 4)
    want_r = np.asarray(
        generate(lm, variables, prompt, 4, prompt_lengths=lens)
    )
    got_r = np.asarray(
        pipelined_generate(
            lm, variables, prompt, 4, mesh, dp_axis="dp",
            prompt_lengths=lens,
        )
    )
    np.testing.assert_array_equal(got_r, want_r)

    # EOS latching and int8 caches carry row-state whose shapes changed
    # under dp sharding (done masks, quant scale buffers) — pin them on
    # the 2-D mesh too.
    eos = int(want[0, 1])
    want_e = np.asarray(generate(lm, variables, prompt, 5, eos_id=eos))
    got_e = np.asarray(
        pipelined_generate(
            lm, variables, prompt, 5, mesh, dp_axis="dp", eos_id=eos
        )
    )
    np.testing.assert_array_equal(got_e, want_e)

    want_q = np.asarray(
        generate(lm, variables, prompt, 4, kv_cache_dtype="int8")
    )
    got_q = np.asarray(
        pipelined_generate(
            lm, variables, prompt, 4, mesh, dp_axis="dp",
            kv_cache_dtype="int8",
        )
    )
    np.testing.assert_array_equal(got_q, want_q)

    with pytest.raises(ValueError, match="dp size"):
        # 12 rows: divisible by pp=4 (3 per microbatch) but 3 % dp=2 != 0.
        pipelined_generate(
            lm, variables, prompt[:12], 4, mesh, dp_axis="dp"
        )

def test_gqa_matches_generate(pp4):
    """A GQA model decodes through the pipeline: rank-local cache
    buffers carry the smaller kv_heads layout, tokens still match
    single-program generate()."""
    from adapt_tpu.models.transformer_lm import transformer_lm

    vocab = 53
    lm = transformer_lm(vocab=vocab, dim=32, depth=4, heads=4, mlp_dim=48,
                        max_len=32, kv_heads=2)
    prompt = jax.random.randint(jax.random.PRNGKey(60), (4, 5), 0, vocab)
    variables = lm.graph.init(jax.random.PRNGKey(61), prompt)
    want = np.asarray(generate(lm, variables, prompt, 6))
    got = np.asarray(
        pipelined_generate(lm, variables, prompt, 6, pp4)
    )
    np.testing.assert_array_equal(got, want)
