"""Speculative decoding is LOSSLESS for greedy: whatever the draft
model proposes, the emitted stream must equal the big model's own
greedy generate() output — the draft may only change speed."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.models.speculative import speculative_generate
from adapt_tpu.models.transformer_lm import (
    generate,
    lm_tiny,
    transformer_lm,
)


@pytest.fixture(scope="module")
def big_setup():
    lm = lm_tiny(vocab=41, max_len=48)
    prompt = jax.random.randint(jax.random.PRNGKey(0), (1, 6), 0, 41)
    variables = lm.graph.init(jax.random.PRNGKey(1), prompt)
    return lm, variables, prompt


@pytest.fixture(scope="module")
def draft_setup():
    # Same vocab, different (smaller) architecture, independent init —
    # a real draft whose proposals are frequently wrong.
    draft = transformer_lm(41, 32, 2, 2, 64, max_len=48, name="draft")
    variables = draft.graph.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32)
    )
    return draft, variables


def test_perfect_draft_full_acceptance(big_setup):
    """Draft == target: every proposal accepted, output identical."""
    lm, variables, prompt = big_setup
    want = np.asarray(generate(lm, variables, prompt, 12))
    got, stats = speculative_generate(
        lm, variables, prompt, 12, lm, variables, draft_k=4,
        return_stats=True,
    )
    np.testing.assert_array_equal(got, want)
    assert stats["acceptance"] == 1.0
    # d+1 = 5 tokens per round after the prefill token -> 3 rounds for
    # the remaining 11.
    assert stats["rounds"] == 3


@pytest.mark.parametrize("draft_k", [1, 2, 4])
def test_wrong_draft_still_lossless(big_setup, draft_setup, draft_k):
    """An independent draft (mostly-rejected proposals) must not change
    a single token — only the round count."""
    lm, variables, prompt = big_setup
    draft, dvars = draft_setup
    want = np.asarray(generate(lm, variables, prompt, 10))
    got, stats = speculative_generate(
        lm, variables, prompt, 10, draft, dvars, draft_k=draft_k,
        return_stats=True,
    )
    np.testing.assert_array_equal(got, want)
    assert stats["rounds"] >= 1
    assert 0.0 <= stats["acceptance"] <= 1.0


@pytest.mark.parametrize("steps", [1, 2, 5])
def test_step_edges(big_setup, draft_setup, steps):
    lm, variables, prompt = big_setup
    draft, dvars = draft_setup
    want = np.asarray(generate(lm, variables, prompt, steps))
    got = speculative_generate(
        lm, variables, prompt, steps, draft, dvars, draft_k=3
    )
    np.testing.assert_array_equal(got, want)


def test_eos_padding_matches_generate(big_setup, draft_setup):
    lm, variables, prompt = big_setup
    draft, dvars = draft_setup
    greedy = np.asarray(generate(lm, variables, prompt, 10))
    eos = int(greedy[0, 1])
    want = np.asarray(generate(lm, variables, prompt, 10, eos_id=eos))
    got = speculative_generate(
        lm, variables, prompt, 10, draft, dvars, draft_k=3, eos_id=eos
    )
    np.testing.assert_array_equal(got, want)


def test_validation(big_setup, draft_setup):
    lm, variables, prompt = big_setup
    draft, dvars = draft_setup
    with pytest.raises(ValueError, match="b=1"):
        speculative_generate(
            lm, variables, jnp.zeros((2, 4), jnp.int32), 4, draft, dvars
        )
    other = lm_tiny(vocab=17, max_len=48)
    ovars = other.graph.init(jax.random.PRNGKey(3), jnp.zeros((1, 4), jnp.int32))
    with pytest.raises(ValueError, match="vocab"):
        speculative_generate(lm, variables, prompt, 4, other, ovars)
    with pytest.raises(ValueError, match="draft_k"):
        speculative_generate(lm, variables, prompt, 4, draft, dvars, draft_k=0)


def test_one_host_transfer_per_round(big_setup, draft_setup):
    """The serving-control-path contract, counter-asserted like
    ``benchmarks/micro/tick_host_overhead.py``: acceptance is reduced
    ON DEVICE and each round performs exactly ONE device->host fetch
    (the packed [accepted, predictions] vector) — the old loop fetched
    the proposals, re-uploaded them into the verify chunk, and fetched
    the predictions separately (three transfers, two syncs)."""
    lm, variables, prompt = big_setup
    draft, dvars = draft_setup
    _, stats = speculative_generate(
        lm, variables, prompt, 12, draft, dvars, draft_k=4,
        return_stats=True,
    )
    # One fetch per round plus the prefill's first token.
    assert stats["host_fetches"] == stats["rounds"] + 1
    assert stats["rounds"] >= 1


def test_gqa_target_lossless(draft_setup):
    """Speculative decoding against a GQA target: verify_chunk's grouped
    query rows over the small cache must stay lossless vs generate()."""
    vocab = 41
    lm = transformer_lm(vocab, 32, 2, 4, 64, max_len=48, kv_heads=2)
    variables = lm.graph.init(
        jax.random.PRNGKey(70), jnp.zeros((1, 4), jnp.int32)
    )
    prompt = jax.random.randint(jax.random.PRNGKey(71), (1, 5), 0, vocab)
    want = np.asarray(generate(lm, variables, prompt, 8))
    out, stats = speculative_generate(
        lm, variables, prompt, 8, draft_lm=lm, draft_variables=variables,
        draft_k=3, return_stats=True,
    )
    np.testing.assert_array_equal(np.asarray(out), want)
    assert stats["drafted"] > 0
