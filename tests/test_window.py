"""Sliding-window attention (Mistral-style) across every decode path.

The contract stack: the banded oracle defines semantics; cached decode
realizes the window as a dynamic ``valid_from`` (no kernel changes);
``verify_chunk`` and the paged chunk kernel band their masks; and the
paged batcher RECYCLES pages that fall wholly behind the window
mid-request, with refcounts protecting pages a slower sharer still
needs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.models.transformer_lm import (
    generate,
    logits_full,
    transformer_lm,
)
from adapt_tpu.runtime.continuous import ContinuousBatcher

W = 12


@pytest.fixture(scope="module")
def wlm_setup():
    lm = transformer_lm(
        41, 32, 2, 4, 64, max_len=96, kv_heads=2, window=W,
        name="windowed_lm",
    )
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


def test_windowed_cached_decode_matches_full_forward(wlm_setup):
    """Greedy cached generate (window as dynamic valid_from) == stepwise
    argmax of the banded full forward, WELL past the window length so
    old positions actually fall out of every mask."""
    lm, variables = wlm_setup
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (2, 20), 0, 41, jnp.int32
    )
    steps = 30  # 20 + 30 = 50 positions >> window 12
    got = np.asarray(generate(lm, variables, prompt, steps))
    ids = prompt
    for _ in range(steps):
        nxt = jnp.argmax(logits_full(lm, variables, ids)[:, -1], -1)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.asarray(ids)[:, 20:])


def test_window_actually_masks(wlm_setup):
    """Sanity that the window does something: perturbing a token far
    behind the window must NOT change the next-token logits, while
    perturbing one inside it must."""
    lm, variables = wlm_setup
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, 40), 0, 41)
    base = np.asarray(logits_full(lm, variables, ids)[:, -1])
    far = ids.at[0, 5].set((ids[0, 5] + 1) % 41)  # pos 5 << 39 - 12
    near = ids.at[0, 35].set((ids[0, 35] + 1) % 41)
    np.testing.assert_array_equal(
        base, np.asarray(logits_full(lm, variables, far)[:, -1])
    )
    assert not np.array_equal(
        base, np.asarray(logits_full(lm, variables, near)[:, -1])
    )


def test_windowed_ragged_generate(wlm_setup):
    """Ragged left padding composes with the window (valid_from is the
    max of both) — greedy ragged rows equal their solo runs, well past
    the window. (Greedy on purpose: sampled keys fold the GLOBAL row
    index, so a solo run of row r>0 legitimately draws differently.)"""
    lm, variables = wlm_setup
    prompt = jax.random.randint(
        jax.random.PRNGKey(3), (3, 16), 0, 41, jnp.int32
    )
    lengths = jnp.asarray([16, 7, 11], jnp.int32)
    out = np.asarray(
        generate(lm, variables, prompt, 20, prompt_lengths=lengths)
    )
    for r in range(3):
        solo = np.asarray(
            generate(lm, variables, prompt[r:r + 1, : int(lengths[r])], 20)
        )[0]
        np.testing.assert_array_equal(out[r], solo, err_msg=f"row {r}")


def test_windowed_speculative_lossless(wlm_setup):
    """verify_chunk's banded mask: speculative decode stays greedy-
    lossless on the windowed model."""
    from adapt_tpu.models.speculative import speculative_generate

    lm, variables = wlm_setup
    prompt = jax.random.randint(
        jax.random.PRNGKey(5), (1, 10), 0, 41, jnp.int32
    )
    want = np.asarray(generate(lm, variables, prompt, 18))
    got, stats = speculative_generate(
        lm, variables, prompt, 18, lm, variables, draft_k=4,
        return_stats=True,
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["acceptance"] == 1.0  # self-draft upper bound


def test_windowed_paged_serving_recycles_pages(wlm_setup):
    """The rolling-window pool: serving a long windowed generation
    through paged slots releases pages behind the window mid-request
    (base advances, in_use stays bounded), streams match solo
    generate(), and freed pages admit a LATER request into a pool that
    never held two full windows' worth of live pages at once."""
    lm, variables = wlm_setup
    rng = np.random.RandomState(6)
    p1 = rng.randint(0, 41, size=20).astype(np.int32)
    p2 = rng.randint(0, 41, size=20).astype(np.int32)
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged", page_size=16,
    )
    r1 = bat.submit(p1, 60)  # spans 80 positions = 5 pages
    mid_bases = []
    for _ in range(8):
        bat.tick()
        mid_bases.append(bat._pager.base(0))
    assert mid_bases[-1] > 0, "no pages recycled behind the window"
    r2 = bat.submit(p2, 10)
    out = bat.run()
    np.testing.assert_array_equal(
        out[r1], np.asarray(generate(lm, variables, jnp.asarray(p1)[None], 60))[0]
    )
    np.testing.assert_array_equal(
        out[r2], np.asarray(generate(lm, variables, jnp.asarray(p2)[None], 10))[0]
    )
    st = bat._pager.stats()
    assert st.in_use == 0


def test_windowed_shared_prefix_release_respects_refcounts(wlm_setup):
    """Two live requests share prompt pages; the faster one's window
    rolls past them and releases its claim — the slower sharer's
    refcount must keep the pages alive until it releases too."""
    lm, variables = wlm_setup
    rng = np.random.RandomState(7)
    system = rng.randint(0, 41, size=32).astype(np.int32)  # 2 full pages
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged", page_size=16,
    )
    r1 = bat.submit(system, 40)  # long: window rolls past the prompt
    bat.tick()
    r2 = bat.submit(system, 40)
    out = bat.run()
    want = np.asarray(
        generate(lm, variables, jnp.asarray(system)[None], 40)
    )[0]
    np.testing.assert_array_equal(out[r1], want)
    np.testing.assert_array_equal(out[r2], want)


def test_windowed_chunked_prefill_greedy_parity(wlm_setup):
    """Chunked prefill under the window (banded chunk kernel/oracle):
    greedy output equals solo generate()."""
    lm, variables = wlm_setup
    rng = np.random.RandomState(8)
    long_p = rng.randint(0, 41, size=50).astype(np.int32)
    bat = ContinuousBatcher(
        lm, variables, slots=1, chunk=2, kv_layout="paged", page_size=16,
        prefill_chunk=16,
    )
    rid = bat.submit(long_p, 8)
    out = bat.run()
    np.testing.assert_array_equal(
        out[rid],
        np.asarray(generate(lm, variables, jnp.asarray(long_p)[None], 8))[0],
    )


def test_window_validation():
    with pytest.raises(ValueError, match="window"):
        transformer_lm(41, 32, 2, 4, 48, window=0)


# -- banded streaming kernel (fwd + bwd) --------------------------------------


def test_windowed_flash_kernel_matches_oracle(rng):
    """The streaming kernel's band mask (+ dead-block skip on both
    sides of the band) vs the banded oracle, across block boundaries
    and composed with ragged valid_from."""
    from adapt_tpu.ops.attention import attention_reference, flash_attention

    b, h, s, d = 2, 2, 512, 32
    q = jax.random.normal(rng, (b, h, s, d))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (b, h, s, d))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (b, h, s, d))
    for win in (100, 128, 17):
        ref = attention_reference(q, k, v, causal=True, window=win)
        out = flash_attention(
            q, k, v, causal=True, window=win, prefer="pallas"
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"window {win}",
        )
    vf = jnp.asarray([0, 200], jnp.int32)
    ref = attention_reference(q, k, v, causal=True, window=100,
                              valid_from=vf)
    out = flash_attention(q, k, v, causal=True, window=100,
                          valid_from=vf, prefer="pallas")
    rows = np.arange(s)
    live_rows = rows >= np.asarray(vf)[:, None]  # padded rows unspecified
    np.testing.assert_allclose(
        np.asarray(out)[live_rows[:, None, :].repeat(2, 1)],
        np.asarray(ref)[live_rows[:, None, :].repeat(2, 1)],
        rtol=2e-5, atol=2e-5,
    )


def test_windowed_streaming_backward_matches_oracle(rng, monkeypatch):
    """Banded gradients through the two streaming passes (budget forced
    to 0 so the bwd streams) vs grads of the banded oracle."""
    import adapt_tpu.ops.attention as A

    monkeypatch.setattr(A, "FLASH_SCORE_BYTES_BUDGET", 0)
    b, h, s, d = 1, 2, 256, 32
    q = jax.random.normal(rng, (b, h, s, d))
    k = jax.random.normal(jax.random.fold_in(rng, 3), (b, h, s, d))
    v = jax.random.normal(jax.random.fold_in(rng, 4), (b, h, s, d))

    def loss_flash(q, k, v):
        return jnp.sum(
            A.flash_attention(q, k, v, causal=True, window=60,
                              prefer="pallas") ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(
            A.attention_reference(q, k, v, causal=True, window=60) ** 2
        )

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name}",
        )
