"""Disaggregated prefill/decode serving (ISSUE 9): a PrefillWorker
streams KV pages over the comm tier to a decode ContinuousBatcher,
landing them through the paged prefix cache.

Pinned contracts:

- **Wire**: pack/loopback/unpack round-trips bit-exactly with ZERO
  codec-layer payload copies on the send path and receive arrays
  VIEWING the wire buffer (the PR-1 zero-copy framing contract,
  measured via ``codec.copy_stats()``); corrupt or truncated frames
  raise ``HandoffError`` — and through the server, fail the request
  CLEANLY (empty result, ``request_failed`` event, serving continues).
- **Bit-exactness**: greedy streams through the disaggregated path
  equal the collocated path token-for-token (native and int8 pools,
  tp=1 and tp=2 decode side, speculative mode), and handed-off pool
  pages hold byte-identical K/V to an in-place chunked prefill with
  the same chunk schedule.
- **Hot path**: after handoff admissions, steady decode ticks stay at
  zero h2d transfers with a frozen compile footprint.
- **Policy**: the placement decision follows ``config.DisaggConfig``
  (length threshold, occupancy tightening, role-tagged-lease
  liveness) and every fallback is collocated.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from adapt_tpu.comm import codec
from adapt_tpu.comm.framing import frame_parts, parse_frame
from adapt_tpu.config import DisaggConfig, ParallelConfig, SpeculativeConfig
from adapt_tpu.control.registry import WorkerRegistry
from adapt_tpu.models.transformer_lm import transformer_lm
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.runtime.disagg import (
    DisaggServer,
    HandoffError,
    KVHandoff,
    PrefillWorker,
    loopback,
    pack_handoff,
    unpack_handoff,
)
from adapt_tpu.runtime.paged import Pager
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.tracing import global_flight_recorder

VOCAB = 61
PAGE = 8


@pytest.fixture(scope="module")
def lm_setup():
    # Small on purpose (2 blocks, dim 32): disaggregation is a
    # scheduling/placement property, and every batcher + worker pair
    # compiles its own programs — tier-1 wall time is the budget.
    lm = transformer_lm(VOCAB, 32, 2, 2, 64, max_len=96,
                        name="disagg_lm")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


def _mk_pair(lm, variables, dtype="native", mesh=None, tp=1, spec=None,
             draft=None):
    kw = dict(
        slots=2, chunk=4, kv_layout="paged", page_size=PAGE,
        kv_cache_dtype=dtype,
    )
    if mesh is not None:
        kw.update(mesh=mesh, parallel=ParallelConfig(tp=tp))
    if spec is not None:
        dlm, dvars = draft
        kw.update(draft_lm=dlm, draft_variables=dvars, speculative=spec)
    decode = ContinuousBatcher(lm, variables, **kw)
    worker = PrefillWorker(
        lm, variables, page_size=PAGE, prefill_chunk=2 * PAGE,
        kv_cache_dtype=dtype,
    )
    srv = DisaggServer(
        decode, worker,
        DisaggConfig(prompt_threshold=2 * PAGE,
                     busy_prompt_threshold=2 * PAGE),
    )
    return decode, worker, srv


def _rand_handoff(rng, quantized=False, blocks=2, n=3, kvh=2, hd=4):
    def member():
        if quantized:
            return (
                rng.randint(-127, 127, size=(n, kvh, PAGE, hd)).astype(
                    np.int8
                ),
                rng.rand(n, kvh, PAGE, 1).astype(np.float32),
            )
        return rng.rand(n, kvh, PAGE, hd).astype(np.float32)

    return KVHandoff(
        req_id=7,
        prompt=rng.randint(0, VOCAB, size=n * PAGE + 3).astype(np.int32),
        page_size=PAGE,
        n_pages=n,
        quantized=quantized,
        blocks=[(member(), member()) for _ in range(blocks)],
    )


@pytest.mark.parametrize("quantized", [False, True])
def test_handoff_wire_roundtrip_zero_copy(quantized):
    """pack -> gather -> parse -> unpack is bit-exact; the send path
    makes ZERO codec-layer payload copies (scatter-write parts), and
    every received tensor VIEWS the wire buffer (zero-copy receive)."""
    rng = np.random.RandomState(3)
    h = _rand_handoff(rng, quantized=quantized)
    codec.reset_copy_stats()
    msg = pack_handoff(h)
    assert codec.copy_stats()["calls"] == 0  # scatter parts, no joins
    wire = bytearray(b"".join(frame_parts(msg)))
    got = unpack_handoff(parse_frame(memoryview(wire)[8:]))
    assert codec.copy_stats()["calls"] == 0  # unpack slices, never joins
    assert got.n_pages == h.n_pages and got.quantized == quantized
    np.testing.assert_array_equal(got.prompt, h.prompt)
    wire_arr = np.frombuffer(wire, np.uint8)
    for (hk, hv), (gk, gv) in zip(h.blocks, got.blocks):
        for ours, theirs in ((hk, gk), (hv, gv)):
            for a, b in zip(jax.tree.leaves(ours), jax.tree.leaves(theirs)):
                np.testing.assert_array_equal(a, b)
                assert np.shares_memory(b, wire_arr), (
                    "received tensor does not view the wire buffer"
                )


def test_corrupt_and_truncated_handoff_raise():
    rng = np.random.RandomState(4)
    h = _rand_handoff(rng)
    msg = pack_handoff(h)
    wire = bytearray(b"".join(frame_parts(msg)))
    # Truncation: drop the payload tail — frame lengths stop tiling.
    with pytest.raises((HandoffError, ConnectionError)):
        unpack_handoff(parse_frame(memoryview(wire)[8:-17]))
    # Corruption: scribble over the page annex (JSON) region.
    wire2 = bytearray(wire)
    wire2[30:40] = b"\xff" * 10
    with pytest.raises((HandoffError, ConnectionError)):
        unpack_handoff(parse_frame(memoryview(wire2)[8:]))


def test_pager_adopt_cached():
    p = Pager(6, 2, 4)  # 5 allocatable pages
    keys = [b"k0", b"k1", b"k2"]
    got = p.adopt_cached(keys)
    assert [i for i, _ in got] == [0, 1, 2]
    st = p.stats()
    assert st.cached == 3 and st.in_use == 0
    # Dedupe: resident keys are skipped, only the new one adopts.
    got2 = p.adopt_cached([b"k1", b"k3"])
    assert [i for i, _ in got2] == [1]
    # Pool pressure: 1 page left free, 4 cached (evictable) -> a
    # 6-new-key adoption cannot fit all-or-nothing.
    assert p.adopt_cached([f"n{i}".encode() for i in range(6)]) == []
    # An admission's prefix probe shares an adopted page (rc 0 -> 1).
    page = dict(got)[0]
    assert p.lookup_share(0, b"k0") == page
    assert p.stats().in_use == 1


def test_disagg_stream_bit_identical_and_hot_path(lm_setup):
    """The core pin: greedy streams through the disaggregated path
    equal the collocated path token-for-token; the handoff lands as
    prefix-cache hits; steady decode ticks afterwards stay at zero
    h2d with no sentinel events."""
    lm, variables = lm_setup
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, VOCAB, size=n).astype(np.int32)
               for n in (37, 29, 50)]
    steps = [12, 9, 10]
    ref_bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged",
        page_size=PAGE,
    )
    rids = [ref_bat.submit(p, s) for p, s in zip(prompts, steps)]
    refs = ref_bat.run()
    decode, worker, srv = _mk_pair(lm, variables)
    sids = [srv.submit(p, s) for p, s in zip(prompts, steps)]
    outs = srv.run()
    for rid, sid, p in zip(rids, sids, prompts):
        np.testing.assert_array_equal(
            refs[rid], outs[sid], err_msg=f"prompt len {len(p)}"
        )
    assert srv.disaggregated == 3 and srv.collocated == 0
    assert worker.handoffs == 3
    st = decode.stats()
    assert st["prefix_hits"] >= sum((len(p) - 1) // PAGE for p in prompts)
    # Steady-state hot path survives: occupy a slot, then tick with no
    # admissions — zero staging transfers, no new compiled variants.
    sid = srv.submit(prompts[0][:5], 30)  # short -> collocated; stays
    srv.tick()  # live across the steady window below (retirement is
    # allowed its own O(1) staging — the pin here is the TICKS)
    h2d0 = decode.stats()["h2d_transfers"]
    for _ in range(3):
        srv.tick()
    assert decode.stats()["h2d_transfers"] == h2d0
    assert decode._sentinel.sample(write_gauges=False) == 0
    srv.run()


def test_handoff_pages_equal_inplace_chunked_prefill(lm_setup):
    """Satellite pin: pages packed/framed/unpacked into a FRESH pool
    hold byte-identical K/V to an in-place chunked prefill with the
    same chunk schedule — so attention outputs over them are identical
    too (the stream test above covers the end-to-end claim)."""
    lm, variables = lm_setup
    rng = np.random.RandomState(5)
    # m*PAGE is a multiple of the chunk (2 pages), so the worker's
    # chunk passes coincide exactly with the collocated ones.
    prompt = rng.randint(0, VOCAB, size=4 * PAGE + 3).astype(np.int32)
    colo = ContinuousBatcher(
        lm, variables, slots=1, chunk=4, kv_layout="paged",
        page_size=PAGE, prefill_chunk=2 * PAGE,
    )
    colo.submit(prompt, 2)
    colo.run()
    decode, worker, srv = _mk_pair(lm, variables)
    sid = srv.submit(prompt, 2)
    srv.run()
    m = (len(prompt) - 1) // PAGE
    key = Pager.prefix_key(prompt, m * PAGE)
    for bat in (colo, decode):
        assert bat._pager._by_key.get(key) is not None
    for b in range(len(colo._caches)):
        for member in range(2):
            cpool = colo._caches[b][member]
            dpool = decode._caches[b][member]
            for j in range(m):
                pkey = Pager.prefix_key(prompt, (j + 1) * PAGE)
                cpage = colo._pager._by_key[pkey]
                dpage = decode._pager._by_key[pkey]
                np.testing.assert_array_equal(
                    np.asarray(cpool[cpage]),
                    np.asarray(dpool[dpage]),
                    err_msg=f"block {b} member {member} page {j}",
                )


def test_corrupt_wire_fails_request_cleanly(lm_setup, monkeypatch):
    """A corrupted handoff frame fails ONLY that request (empty
    result, request_failed + finish events — result() never wedges);
    the next request serves normally."""
    lm, variables = lm_setup
    decode, worker, srv = _mk_pair(lm, variables)
    import adapt_tpu.runtime.disagg as disagg_mod

    real_loopback = disagg_mod.loopback

    def corrupting(msg):
        wire = bytearray(b"".join(frame_parts(msg)))
        wire[len(wire) // 2] ^= 0xFF  # flip a payload byte mid-frame
        try:
            return parse_frame(memoryview(wire)[8:])
        except ConnectionError as e:
            raise HandoffError(str(e)) from e

    monkeypatch.setattr(disagg_mod, "loopback", corrupting)
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, VOCAB, size=40).astype(np.int32)
    rec0 = global_flight_recorder().kind_counts().get("request_failed", 0)
    sid = srv.submit(prompt, 8)
    out = srv.result(sid, max_ticks=200)
    assert out.shape == (0,)
    counts = global_flight_recorder().kind_counts()
    assert counts.get("request_failed", 0) == rec0 + 1
    assert srv.failed == 1
    # Un-corrupt the wire: serving continues, streams stay exact —
    # and streaming callbacks see the SERVER id (the one submit
    # returned and cancel()/result() accept), not the decode rid.
    monkeypatch.setattr(disagg_mod, "loopback", real_loopback)
    cb_ids = []
    sid2 = srv.submit(
        prompt, 8, on_token=lambda rid, tok, idx: cb_ids.append(rid)
    )
    out2 = srv.result(sid2, max_ticks=400)
    assert set(cb_ids) == {sid2} and len(cb_ids) == len(out2)
    ref = ContinuousBatcher(
        lm, variables, slots=1, chunk=4, kv_layout="paged",
        page_size=PAGE,
    )
    rid = ref.submit(prompt, 8)
    np.testing.assert_array_equal(ref.run()[rid], out2)


def test_placement_policy_and_role_lease(lm_setup):
    """Threshold + occupancy knobs route requests; a dead role-tagged
    prefill lease falls back to collocated; the lease is invisible to
    untagged membership queries with a role filter."""
    lm, variables = lm_setup
    reg = WorkerRegistry(default_ttl_s=5.0)
    decode = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged",
        page_size=PAGE,
    )
    worker = PrefillWorker(lm, variables, page_size=PAGE)
    srv = DisaggServer(
        decode, worker,
        DisaggConfig(prompt_threshold=48, busy_prompt_threshold=17,
                     busy_occupancy=0.5),
        registry=reg,
    )
    assert reg.alive(role="prefill") == ["prefill:prefill0"]
    assert reg.alive(role="decode") == []
    assert reg.role("prefill:prefill0") == "prefill"
    # Idle decode tier: only the long threshold disaggregates.
    assert not srv._placement(30)
    assert srv._placement(60)
    assert not srv._placement(PAGE)  # no full page to hand off
    # Busy decode tier: the tightened threshold applies.
    decode.slots[0].req = object()  # occupancy 0.5 >= busy_occupancy
    assert srv._placement(30)
    decode.slots[0].req = None
    # Dead lease: the policy stops routing to the prefill tier.
    reg.deregister("prefill:prefill0")
    assert not srv._placement(60)
    # And the registry-level role filter keeps the pools disjoint the
    # other way: an untagged worker never shows up under the role, and
    # the dispatcher-side untagged query never sees a tagged lease.
    reg.register("w0")
    assert reg.alive(role="prefill") == []
    assert "w0" in reg.alive()
    # The next tick's keepalive resurrects an EXPIRED lease (the tier
    # is self-evidently alive — it is ticking)...
    srv.tick()
    assert reg.alive(role="prefill") == ["prefill:prefill0"]
    assert reg.alive_untagged() == ["w0"]
    # ...but close() is the drain switch: the lease stays gone.
    srv.close()
    srv.tick()
    assert reg.alive(role="prefill") == []
    assert not srv._placement(60)


def test_prefill_stall_metric(lm_setup):
    """continuous.prefill_stall_s records decode-tick delay only when
    a decoding request was actually waiting behind in-tick prefill."""
    lm, variables = lm_setup
    reg = global_metrics()
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged",
        page_size=PAGE,
    )

    def stall_count():
        h = reg.snapshot()["histograms"].get(
            "continuous.prefill_stall_s", {}
        )
        return h.get("count", 0)

    rng = np.random.RandomState(2)
    bat.submit(rng.randint(0, VOCAB, size=6).astype(np.int32), 12)
    c0 = stall_count()
    bat.tick()  # admission into an EMPTY batch: nobody waited
    assert stall_count() == c0
    bat.tick()
    c1 = stall_count()
    bat.submit(rng.randint(0, VOCAB, size=40).astype(np.int32), 4)
    bat.tick()  # long admission while slot 0 decodes: a stall sample
    assert stall_count() == c1 + 1
    bat.tick()  # steady tick, no prefill work: no sample
    assert stall_count() == c1 + 1
    bat.run()


def test_prefill_cancel_before_handoff(lm_setup):
    """A cancel landing while the request is still in the prefill tier
    drops it with an empty result and balanced lifecycle events."""
    lm, variables = lm_setup
    decode, worker, srv = _mk_pair(lm, variables)
    rng = np.random.RandomState(6)
    sid = srv.submit(rng.randint(0, VOCAB, size=40).astype(np.int32), 8)
    assert worker.pending() == 1
    assert srv.cancel(sid)
    assert worker.pending() == 0
    assert srv.result(sid, max_ticks=5).shape == (0,)
    assert not srv.cancel(sid)  # already resolved


@pytest.mark.slow
@pytest.mark.parametrize(
    "dtype,tp", [("int8", 1), ("native", 2), ("int8", 2)]
)
def test_disagg_bit_identity_matrix(lm_setup, sim_mesh, dtype, tp):
    """int8 pools and tp-sharded decode pools: the handoff (scales
    travel with their values; per-shard slices land with no gather)
    stays bit-identical to the collocated path."""
    from jax.sharding import Mesh

    lm, variables = lm_setup
    mesh = sim_mesh(tp) if tp > 1 else None
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, VOCAB, size=n).astype(np.int32)
               for n in (37, 29, 50)]
    steps = [10, 8, 9]
    kw = dict(slots=2, chunk=4, kv_layout="paged", page_size=PAGE,
              kv_cache_dtype=dtype)
    if mesh is not None:
        kw.update(mesh=mesh, parallel=ParallelConfig(tp=tp))
    ref = ContinuousBatcher(lm, variables, **kw)
    rids = [ref.submit(p, s) for p, s in zip(prompts, steps)]
    refs = ref.run()
    decode, worker, srv = _mk_pair(
        lm, variables, dtype=dtype, mesh=mesh, tp=tp
    )
    sids = [srv.submit(p, s) for p, s in zip(prompts, steps)]
    outs = srv.run()
    for rid, sid in zip(rids, sids):
        np.testing.assert_array_equal(refs[rid], outs[sid])
    assert srv.disaggregated == len(prompts)
    # Per-device bytes stay logical/tp after adoption (the handoff
    # placed per-shard slices, never replicated pages).
    st = decode.stats()
    assert st["cache_bytes_per_device"] * tp == st["cache_bytes"]


@pytest.mark.slow
def test_disagg_speculative_compose(lm_setup):
    """Speculative decode batcher behind the disaggregated path:
    handed-off requests admit through the prefix cache, the draft
    prefills decode-side as always, greedy streams stay lossless."""
    lm, variables = lm_setup
    draft = transformer_lm(VOCAB, 16, 1, 1, 32, max_len=96,
                           name="disagg_draft")
    dvars = draft.graph.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32)
    )
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, VOCAB, size=n).astype(np.int32)
               for n in (37, 26)]
    steps = [10, 8]
    spec = SpeculativeConfig(draft_k=3)
    ref = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged",
        page_size=PAGE, draft_lm=draft, draft_variables=dvars,
        speculative=spec,
    )
    rids = [ref.submit(p, s) for p, s in zip(prompts, steps)]
    refs = ref.run()
    decode, worker, srv = _mk_pair(
        lm, variables, spec=spec, draft=(draft, dvars)
    )
    sids = [srv.submit(p, s) for p, s in zip(prompts, steps)]
    outs = srv.run()
    for rid, sid in zip(rids, sids):
        np.testing.assert_array_equal(refs[rid], outs[sid])
    assert srv.disaggregated == len(prompts)
