"""Checkpoint/resume tests: pytree round-trip, metadata sidecar,
pipeline re-materialization from disk, train-state retention + resume."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from adapt_tpu.graph import partition
from adapt_tpu.models.vit import vit_tiny
from adapt_tpu.runtime import LocalPipeline
from adapt_tpu.utils.checkpoint import (
    TrainCheckpointer,
    restore_variables,
    save_variables,
)


@pytest.fixture
def vit_and_vars(rng):
    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3))
    variables = jax.jit(g.init)(rng, x)
    return g, variables, x


def test_variables_roundtrip_with_metadata(tmp_path, vit_and_vars):
    g, variables, x = vit_and_vars
    path = tmp_path / "ckpt"
    meta = {"model": "vit_tiny", "cuts": ["encoder_block_1"]}
    save_variables(path, variables, metadata=meta)
    restored, got_meta = restore_variables(path, example=variables)
    assert got_meta == meta
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        variables,
        restored,
    )


def test_restore_without_example(tmp_path, vit_and_vars):
    _, variables, _ = vit_and_vars
    path = tmp_path / "ckpt"
    save_variables(path, variables)
    restored, meta = restore_variables(path)
    assert meta == {}
    leaves_a = jax.tree.leaves(variables)
    leaves_b = jax.tree.leaves(restored)
    assert len(leaves_a) == len(leaves_b)
    np.testing.assert_array_equal(
        np.asarray(leaves_a[0]), np.asarray(leaves_b[0])
    )


def test_pipeline_rematerializes_from_checkpoint(tmp_path, vit_and_vars, devices):
    """A checkpoint taken on one mesh restores into a pipeline on any
    survivor count (restores are host-first; placement is late-bound)."""
    g, variables, x = vit_and_vars
    ref = np.asarray(jax.jit(g.apply)(variables, x))
    path = tmp_path / "ckpt"
    save_variables(
        path, variables, metadata={"cuts": ["encoder_block_1", "encoder_block_2"]}
    )
    restored, meta = restore_variables(path, example=variables)
    plan = partition(g, meta["cuts"])
    pipe = LocalPipeline(plan, restored, devices=devices[:3])
    np.testing.assert_allclose(
        np.asarray(pipe.infer(x)), ref, rtol=1e-5, atol=1e-5
    )


def test_train_checkpointer_retention_and_resume(tmp_path, rng):
    params = {"w": jax.random.normal(rng, (4, 4)), "b": jnp.zeros((4,))}
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    with TrainCheckpointer(tmp_path / "train", max_to_keep=2) as ck:
        for step in (1, 2, 3):
            scaled = jax.tree.map(lambda a: a * (1.0 + step), params)
            ck.save(step, scaled, opt_state)
        assert ck.latest_step() == 3
        p3, os3, step = ck.restore(params, opt_state)
        assert step == 3
        np.testing.assert_allclose(
            np.asarray(p3["w"]), np.asarray(params["w"]) * 4.0, rtol=1e-6
        )
        # retention: step 1 evicted
        with pytest.raises(Exception):
            ck.restore(params, opt_state, step=1)


def test_restore_missing_dir_raises(tmp_path):
    with TrainCheckpointer(tmp_path / "empty") as ck:
        with pytest.raises(FileNotFoundError):
            ck.restore({}, {})

