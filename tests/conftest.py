"""Test bootstrap: simulated 8-device CPU mesh.

The reference's only "multi-node without a cluster" affordance is localhost
aliasing (``/root/reference/src/dispatcher.py:163-173``). Our analog is a
virtual device mesh: force the JAX CPU backend to expose 8 devices so every
multi-stage / multi-worker / fault-injection test runs hermetically in CI
with real (host) transfers between real XLA devices.

Must run before jax initializes a backend, hence env vars at import time.
"""

import os
import re

# Force, don't setdefault: the outer environment may pin JAX_PLATFORMS to a
# real accelerator, but tests must always run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_FLAG = "--xla_force_host_platform_device_count"
_flags = os.environ.get("XLA_FLAGS", "")
_m = re.search(rf"{_FLAG}=(\d+)", _flags)
if _m is None:
    os.environ["XLA_FLAGS"] = f"{_flags} {_FLAG}=8".strip()
elif int(_m.group(1)) < 8:
    os.environ["XLA_FLAGS"] = re.sub(rf"{_FLAG}=\d+", f"{_FLAG}=8", _flags)

import jax  # noqa: E402

# An interpreter-startup hook (sitecustomize) may import jax before this
# conftest runs, freezing jax_platforms from the pre-existing env. Override
# via the config API, which works after import as long as no backend has
# been initialized yet.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long parameterizations excluded from the tier-1 run "
        "(ROADMAP.md runs -m 'not slow')",
    )
    config.addinivalue_line(
        "markers",
        "statistical: seed-pinned distributional assertions (e.g. the "
        "temperature>0 speculative-sampling equivalence gate) — "
        "deterministic under the pinned seed, but the TEST's tolerance "
        "is a statistical bound, not bit-identity; when one fails "
        "after an intentional sampling change, re-derive the pinned "
        "expectations instead of loosening the bound",
    )


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Bound per-process XLA state: after ~240 accumulated compiled
    executables the XLA:CPU compiler segfaulted mid-compile (observed in
    jax 0.9.0's backend_compile_and_load during a late test module; the
    same test passes standalone). Clearing jit/tracing caches at module
    boundaries keeps compiler state small for a suite this size; the
    recompiles it causes are per-module models that would mostly compile
    fresh anyway."""
    jax.clear_caches()
    yield


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    assert devs[0].platform == "cpu", (
        "tests must run on the virtual CPU mesh, got platform "
        f"{devs[0].platform!r} — a backend was initialized before conftest "
        "could force jax_platforms=cpu"
    )
    return devs


@pytest.fixture(scope="session")
def sim_mesh(devices):
    """Factory for meshes over the virtual device pool — THE test-side
    mesh constructor (the ``--xla_force_host_platform_device_count``
    handling above feeds it). ``sim_mesh(4)`` builds a 1-axis
    ``('tp', 4)`` mesh, ``sim_mesh(4, axis='pp')`` renames the axis, and
    ``sim_mesh((('dp', 2), ('pp', 4)))`` builds a multi-axis mesh.
    Skips the test cleanly when the pool holds fewer devices than the
    mesh needs (e.g. a constrained environment where the XLA flag was
    pinned lower), instead of failing on an opaque reshape."""

    def build(spec, axis: str = "tp"):
        from adapt_tpu.core.mesh import MeshSpec, build_mesh

        axes = ((axis, spec),) if isinstance(spec, int) else tuple(spec)
        mspec = MeshSpec(axes)
        if mspec.num_devices > len(devices):
            pytest.skip(
                f"mesh {axes} needs {mspec.num_devices} devices, "
                f"have {len(devices)}"
            )
        return build_mesh(mspec, devices)

    return build


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


def spawn_worker_proc(*cli_args: str) -> "subprocess.Popen":
    """Launch ``python -m adapt_tpu.comm.remote`` as a hermetic CPU child
    (shared by the comm and stress tests — one place owns the env recipe:
    drop any interpreter-startup PYTHONPATH hook, force the CPU backend,
    put the repo on the path)."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(os.path.dirname(__file__)))
    return subprocess.Popen(
        [sys.executable, "-m", "adapt_tpu.comm.remote", *cli_args],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )


def chain_cfg(configure_timeout_s: float = 60.0):
    """ServeConfig used by the chain-forwarding tests (shared by
    test_comm and test_control)."""
    from adapt_tpu.config import FaultConfig, ServeConfig

    return ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=2.0,
            heartbeat_s=0.2,
            task_deadline_s=30.0,
            watchdog_period_s=0.2,
            startup_wait_s=15.0,
            configure_timeout_s=configure_timeout_s,
        )
    )


def chain_pool(
    disp, cfg, cuts, ports, codec_name: str = "none", prefix: str = "chain"
):
    """Spawn one worker process per port and attach dial-out proxies —
    the shared setup for every chain-forwarding test. Returns
    (procs, proxies)."""
    from adapt_tpu.comm.remote import RemoteWorkerProxy

    procs = [
        spawn_worker_proc("--port", str(p), "--heartbeat", "0.2")
        for p in ports
    ]
    proxies = []
    for i, p in enumerate(ports):
        pr = RemoteWorkerProxy(
            f"{prefix}-{i}",
            ("127.0.0.1", p),
            disp.registry,
            disp.result_queue,
            model_config={
                "model": "vit_tiny",
                "num_classes": 10,
                "cuts": cuts,
                "input_shape": [2, 32, 32, 3],
            },
            codec_name=codec_name,
            fault=cfg.fault,
        )
        disp.attach_worker(pr)
        proxies.append(pr)
    return procs, proxies
