"""Test bootstrap: simulated 8-device CPU mesh.

The reference's only "multi-node without a cluster" affordance is localhost
aliasing (``/root/reference/src/dispatcher.py:163-173``). Our analog is a
virtual device mesh: force the JAX CPU backend to expose 8 devices so every
multi-stage / multi-worker / fault-injection test runs hermetically in CI
with real (host) transfers between real XLA devices.

Must run before jax initializes a backend, hence env vars at import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)
