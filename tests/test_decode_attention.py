"""Decode-attention kernel tests (Pallas interpreter on the CPU mesh).

The serving hot path's attention — one token's query over the live
window of a KV cache — has two implementations that must agree:
``decode_attention_reference`` (the einsum schedule ``decode_step`` has
always run) and the streaming Pallas kernel (``prefer="pallas"``) that
dequantizes int8 caches in VMEM. The reference is the oracle; the
kernel must match it on every cache flavor (native/int8), head layout
(MHA/GQA), index form (scalar/per-row) and masking (dense/ragged).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.models.transformer_lm import generate, transformer_lm
from adapt_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_reference,
)
from adapt_tpu.ops.quantize import quantize_kv_vectors as _quantize_kv


def _caches(rng, b, kvh, length, hd, quantized, live_upto):
    """Caches with real values up to ``live_upto`` and garbage past it
    (the dead tail must not leak into the output)."""
    kk, kv, kg = jax.random.split(rng, 3)
    k = jax.random.normal(kk, (b, kvh, length, hd), jnp.float32)
    v = jax.random.normal(kv, (b, kvh, length, hd), jnp.float32)
    # Huge garbage past the live window: a masking bug becomes loud.
    tail = (jnp.arange(length) > live_upto)[None, None, :, None]
    k = jnp.where(tail, 1e4 * jax.random.normal(kg, k.shape), k)
    v = jnp.where(tail, -1e4, v)
    if not quantized:
        return k, v
    return _quantize_kv(k), _quantize_kv(v)


@pytest.mark.parametrize("quantized", [False, True])
@pytest.mark.parametrize("length", [1024, 2048])
def test_kernel_matches_reference(rng, quantized, length):
    b, kvh, g, hd = 2, 3, 1, 64
    index = jnp.asarray(length // 2 + 7, jnp.int32)
    ck, cv = _caches(rng, b, kvh, length, hd, quantized, length // 2 + 7)
    q = jax.random.normal(jax.random.fold_in(rng, 1), (b, kvh, g, hd))
    ref = decode_attention_reference(q, ck, cv, index)
    out = decode_attention(q, ck, cv, index, prefer="pallas")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("quantized", [False, True])
def test_kernel_gqa_rows_and_per_row_index(rng, quantized):
    # g=4 query rows per KV head (sublane-padded to 8 inside the kernel)
    # and a per-row index: each batch row's live window differs.
    b, kvh, g, hd, length = 3, 2, 4, 64, 1024
    index = jnp.asarray([100, 1023, 512], jnp.int32)
    # Garbage sits strictly past every row's window (max index = 1023).
    ck, cv = _caches(rng, b, kvh, length, hd, quantized, 1023)
    q = jax.random.normal(jax.random.fold_in(rng, 2), (b, kvh, g, hd))
    ref = decode_attention_reference(q, ck, cv, index)
    out = decode_attention(q, ck, cv, index, prefer="pallas")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("quantized", [False, True])
def test_kernel_ragged_valid_from(rng, quantized):
    b, kvh, g, hd, length = 2, 2, 1, 64, 2048
    index = jnp.asarray(1500, jnp.int32)
    valid_from = jnp.asarray([0, 1100], jnp.int32)  # row 1: left-padded
    ck, cv = _caches(rng, b, kvh, length, hd, quantized, 1500)
    q = jax.random.normal(jax.random.fold_in(rng, 3), (b, kvh, g, hd))
    ref = decode_attention_reference(q, ck, cv, index, valid_from)
    out = decode_attention(q, ck, cv, index, valid_from, prefer="pallas")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_kernel_early_index_skips_dead_tail(rng):
    # index in the first block: every later block is dead and skipped —
    # its garbage (1e4-scale K, -1e4 V) must not reach the output.
    b, kvh, g, hd, length = 1, 2, 1, 64, 4096
    index = jnp.asarray(17, jnp.int32)
    ck, cv = _caches(rng, b, kvh, length, hd, False, 17)
    q = jax.random.normal(jax.random.fold_in(rng, 4), (b, kvh, g, hd))
    ref = decode_attention_reference(q, ck, cv, index)
    out = decode_attention(q, ck, cv, index, prefer="pallas")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    assert np.all(np.isfinite(np.asarray(out)))


@pytest.mark.parametrize("length", [256, 512])
def test_short_native_cache_takes_the_kernel(rng, length):
    """Native caches shrink the kernel block to 256 (no scale tiles to
    satisfy), so the short-context serving configs — where the XLA
    einsum path streams the cache least efficiently — are kernel-
    eligible too."""
    from adapt_tpu.ops.decode_attention import _supported, default_block_k

    assert default_block_k(length, quantized=False) == min(length, 1024)
    # Vacuity guard: on a build without pallas-tpu the oracle would
    # serve both sides and this test would pass while testing nothing.
    assert _supported(length, default_block_k(length, False), False)
    b, kvh, g, hd = 2, 2, 2, 64
    index = jnp.asarray(length - 29, jnp.int32)
    ck, cv = _caches(rng, b, kvh, length, hd, False, length - 29)
    q = jax.random.normal(jax.random.fold_in(rng, 5), (b, kvh, g, hd))
    out = decode_attention(q, ck, cv, index, prefer="pallas")
    ref = decode_attention_reference(q, ck, cv, index)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_unsupported_configs_fall_back_to_oracle(rng):
    # Native 192 (not 256-divisible) and int8 256 (scale tiles need
    # 1024-divisible caches): prefer="pallas" silently serves the
    # oracle — outputs are bit-identical to the reference because the
    # same code path ran.
    b, kvh, g, hd = 2, 2, 1, 64
    q = jax.random.normal(jax.random.fold_in(rng, 5), (b, kvh, g, hd))
    index = jnp.asarray(100, jnp.int32)
    ck, cv = _caches(rng, b, kvh, 192, hd, False, 100)
    out = decode_attention(q, ck, cv, index, prefer="pallas")
    ref = decode_attention_reference(q, ck, cv, index)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    ck8, cv8 = _caches(rng, b, kvh, 256, hd, True, 100)
    out8 = decode_attention(q, ck8, cv8, index, prefer="pallas")
    ref8 = decode_attention_reference(q, ck8, cv8, index)
    np.testing.assert_array_equal(np.asarray(out8), np.asarray(ref8))


def test_bad_prefer_raises(rng):
    q = jnp.zeros((1, 1, 1, 64))
    c = jnp.zeros((1, 1, 1024, 64))
    with pytest.raises(ValueError, match="prefer"):
        decode_attention(q, c, c, 0, prefer="cuda")


@pytest.mark.parametrize("kv_dtype", ["native", "int8"])
def test_generate_pallas_decode_matches_xla(kv_dtype):
    # End-to-end: the whole generate() scan with the kernel per step
    # must reproduce the XLA path token-for-token (greedy).
    lm = transformer_lm(97, 64, 2, 4, 128, max_len=1024, kv_heads=2)
    rng = jax.random.PRNGKey(0)
    variables = lm.graph.init(rng, jnp.zeros((1, 8), jnp.int32))
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (2, 8), 0, 97, jnp.int32
    )
    base = generate(
        lm, variables, prompt, steps=6, kv_cache_dtype=kv_dtype,
        decode_attn="xla",
    )
    ker = generate(
        lm, variables, prompt, steps=6, kv_cache_dtype=kv_dtype,
        decode_attn="pallas",
    )
    np.testing.assert_array_equal(np.asarray(base), np.asarray(ker))


def test_generate_bad_decode_attn_raises():
    lm = transformer_lm(97, 64, 2, 4, 128, max_len=64)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )
    prompt = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="decode_attn"):
        generate(lm, variables, prompt, steps=2, decode_attn="cuda")


def test_head_parity_guard_names_the_tp_mistake(rng):
    """Mixing a head-sharded cache with globally-shaped queries (the
    partial-TP-migration bug) must fail by name at the dispatch layer,
    not as a broadcast error deep inside an einsum — for the contiguous,
    verify and paged entry points alike."""
    from adapt_tpu.ops.decode_attention import verify_attention
    from adapt_tpu.ops.paged_attention import (
        paged_attention,
        paged_verify_attention,
    )

    q = jnp.zeros((2, 4, 2, 8))  # 4 KV-head rows
    cache = jnp.zeros((2, 2, 16, 8))  # ...but a 2-head (per-shard) cache
    with pytest.raises(ValueError, match="head count"):
        decode_attention(q, cache, cache, 3)
    with pytest.raises(ValueError, match="head count"):
        verify_attention(q, cache, cache, jnp.zeros((2,), jnp.int32), 2)
    pool = jnp.zeros((4, 2, 8, 8))
    table = jnp.zeros((2, 2), jnp.int32)
    with pytest.raises(ValueError, match="head count"):
        paged_attention(q, pool, pool, table, 3)
    with pytest.raises(ValueError, match="head count"):
        paged_verify_attention(
            q, pool, pool, table, jnp.zeros((2,), jnp.int32), 2
        )
