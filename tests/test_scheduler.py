"""Multi-tenant overload control (ISSUE 10): quotas, weighted fair
queueing, bounded admission, decode-slot preemption, closed-loop
degradation.

Five layers, one file:

- ``AdmissionQueue`` unit semantics — FIFO degradation without a
  config, strict priority classes, deficit-round-robin weight shares,
  burst caps and the global depth bound, cancel removal, recovery's
  clear/extend rebuild;
- bounded ``submit()`` — synchronous ``QueueFullError``, the
  ``request_rejected`` flight event + ``scheduler.rejected_total``,
  ``stats()["queued"]`` never exceeding the bound, and no wedged
  ``result()`` (a rejected request has no id to wait on);
- decode-slot preemption — the acceptance pin: a preempted request's
  final stream is BIT-IDENTICAL to an unpreempted run of the same
  request on BOTH layouts, ``on_token`` delivery stays exactly-once
  across the preemption, the paged re-admission re-enters through the
  prefix cache, and the victim is the lowest-priority slot;
- the degradation ladder — escalation under backlog walks
  draft_k -> evict-cached -> reject-best-effort (events, counters,
  gauge), de-escalation restores on drain;
- preemption/rejection x disaggregation — a handoff landing into a
  full admission queue fails ONLY its request while both pools'
  page partitions stay exact (no leaked rc), and a preempted
  disagg-admitted request replays through its adopted pages.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from adapt_tpu.config import (
    DisaggConfig,
    SchedulerConfig,
    SLOSpec,
    SpeculativeConfig,
    TenantQuota,
)
from adapt_tpu.models.transformer_lm import lm_tiny
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.runtime.scheduler import AdmissionQueue, QueueFullError
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.tracing import global_flight_recorder


@pytest.fixture
def clean_slate():
    import gc

    gc.collect()
    global_metrics().reset()
    global_flight_recorder().clear()
    yield
    global_metrics().reset()
    global_flight_recorder().clear()


class _Req:
    """Duck-typed request for queue unit tests (the queue only reads
    ``.slo``, ``.req_id``, ``.t_submit``/``.t_requeued``)."""

    def __init__(self, req_id, tenant=None, priority=0, ttft=None):
        self.req_id = req_id
        self.slo = (
            SLOSpec(tenant=tenant, priority=priority, ttft_budget_s=ttft)
            if tenant is not None
            else None
        )
        self.t_submit = float(req_id)
        self.t_requeued = 0.0


@pytest.fixture
def batcher_factory():
    made = []

    def make(layout="slots", draft=False, scheduler=None, **kw):
        lm = lm_tiny(vocab=29, max_len=64)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        if draft:
            kw.update(draft_lm=lm, draft_variables=variables)
        if layout == "paged":
            kw.update(kv_layout="paged", page_size=8)
        bat = ContinuousBatcher(
            lm, variables, chunk=4, scheduler=scheduler, **kw
        )
        made.append(bat)
        return bat

    yield make
    for b in made:
        b.close()


# -- AdmissionQueue unit semantics ------------------------------------------


def test_queue_without_config_is_strict_fifo_and_bounded():
    q = AdmissionQueue()  # no config: priority/tenant inert
    reqs = [
        _Req(0, "b", priority=5),
        _Req(1, "a", priority=0),
        _Req(2),  # no SLO at all
        _Req(3, "a", priority=99),
    ]
    for r in reqs:
        q.append(r)
    assert len(q) == 4
    assert q.preempt_candidate() is None  # FIFO mode never nominates
    assert [q.popleft().req_id for _ in range(4)] == [0, 1, 2, 3]
    with pytest.raises(IndexError):
        q.popleft()


def test_queue_priority_classes_strictly_order():
    q = AdmissionQueue(SchedulerConfig())
    q.append(_Req(0, "t", priority=0))
    q.append(_Req(1, "t", priority=-1))  # best-effort
    q.append(_Req(2, "t", priority=7))
    q.append(_Req(3, "t", priority=0))
    assert [q.popleft().req_id for _ in range(4)] == [2, 0, 3, 1]


def test_queue_drr_weight_shares():
    cfg = SchedulerConfig(
        quotas={"a": TenantQuota(weight=3.0), "b": TenantQuota(weight=1.0)}
    )
    q = AdmissionQueue(cfg)
    for i in range(8):
        q.append(_Req(i, "a"))
    for i in range(8, 16):
        q.append(_Req(i, "b"))
    first8 = [q.popleft() for _ in range(8)]
    from adapt_tpu.runtime.scheduler import request_tenant

    tenants = [request_tenant(r) for r in first8]
    # Weight 3:1 -> a drains 3 per round, b 1: 6 a's in the first 8.
    assert tenants.count("a") == 6 and tenants.count("b") == 2
    # Within each tenant: FIFO.
    assert [r.req_id for r in first8 if r.slo.tenant == "a"] == list(
        range(6)
    )


def test_queue_bounds_burst_and_depth_and_shed():
    cfg = SchedulerConfig(
        max_queue_depth=4, quotas={"f": TenantQuota(burst=2)}
    )
    q = AdmissionQueue(cfg)
    q.append(_Req(0, "f"))
    q.append(_Req(1, "f"))
    with pytest.raises(QueueFullError):  # tenant burst cap
        q.append(_Req(2, "f"))
    q.append(_Req(3, "g"))
    q.append(_Req(4, "g"))
    with pytest.raises(QueueFullError):  # global depth bound
        q.append(_Req(5, "g"))
    # appendleft (replay/preemption re-insert) bypasses the bound.
    q.popleft()
    q.appendleft(_Req(6, "f"))
    assert len(q) == 4
    # Best-effort shed (degradation rung 4): priority < 0 rejected.
    q2 = AdmissionQueue(SchedulerConfig())
    q2.shed_best_effort = True
    with pytest.raises(QueueFullError):
        q2.append(_Req(0, "x", priority=-1))
    q2.append(_Req(1, "x", priority=0))  # ordinary class unaffected


def test_queue_front_reinsert_restores_head_of_line():
    """A pool-pressure put-back (appendleft of the request just
    popped) must restore the tenant's service turn — ring front +
    DRR unit refunded — or other tenants' smaller requests jump the
    large one every round and it starves."""
    cfg = SchedulerConfig(
        quotas={"a": TenantQuota(weight=1.0), "b": TenantQuota(weight=1.0)}
    )
    q = AdmissionQueue(cfg)
    q.append(_Req(0, "a"))
    q.append(_Req(1, "b"))
    q.append(_Req(2, "a"))
    r = q.popleft()
    assert r.req_id == 0
    q.appendleft(r)  # alloc failed: put it back
    assert q.popleft().req_id == 0  # head-of-line, not b's turn
    assert q.popleft().req_id == 1  # then the round proceeds


def test_queue_remove_id_depths_and_rebuild():
    cfg = SchedulerConfig(quotas={"a": TenantQuota(weight=2.0)})
    q = AdmissionQueue(cfg)
    for i in range(3):
        q.append(_Req(i, "a"))
    q.append(_Req(3, "b"))
    assert q.depths() == {"a": 3, "b": 1}
    got = q.remove_id(1)
    assert got.req_id == 1 and len(q) == 3
    assert q.remove_id(99) is None
    assert q.depths()["a"] == 2
    # recover()'s rebuild path: clear + extend preserves given order
    # per tenant and the membership iteration sees everything.
    held = list(q)
    q.clear()
    assert len(q) == 0 and q.depths() == {"a": 0, "b": 0}
    q.extend(held)
    assert sorted(r.req_id for r in q) == [0, 2, 3]


def test_queue_cache_aware_picks_hottest_prefix_in_window():
    """``cache_aware=True`` with an installed probe: the pop takes the
    hottest/longest radix-resident prefix among the first
    ``cache_aware_window`` candidates of the selected tenant queue —
    entries past the window cannot jump, equal scores keep strict
    arrival order (a cold queue degrades to byte-exact FIFO), and a
    probe that explodes must never break admission."""
    cfg = SchedulerConfig(cache_aware=True, cache_aware_window=3)
    q = AdmissionQueue(cfg)
    score = {0: (0, 0), 1: (2, 5), 2: (2, 9), 3: (0, 0), 4: (9, 9)}
    q.prefix_probe = lambda r: score[r.req_id]
    for i in range(5):
        q.append(_Req(i, "t"))
    # Window scans 0..2: req 2 (same depth as 1, hotter) wins; req 4's
    # top score sits OUTSIDE the window and cannot jump yet.
    assert q.popleft().req_id == 2
    assert q.popleft().req_id == 1  # window scans 0,1,3: 1 wins
    assert q.popleft().req_id == 4  # 4 slid into the window
    # 0 vs 3 tie at (0, 0): strictly-greater wins only -> FIFO.
    assert [q.popleft().req_id, q.popleft().req_id] == [0, 3]
    # A broken probe degrades to FIFO instead of raising out of pop.
    q2 = AdmissionQueue(cfg)
    q2.prefix_probe = lambda r: 1 // 0
    for i in range(3):
        q2.append(_Req(i, "t"))
    assert [q2.popleft().req_id for _ in range(3)] == [0, 1, 2]
    # cache_aware off: an installed probe is inert.
    q3 = AdmissionQueue(SchedulerConfig())
    q3.prefix_probe = lambda r: -r.req_id
    for i in range(3):
        q3.append(_Req(i, "t"))
    assert [q3.popleft().req_id for _ in range(3)] == [0, 1, 2]


def test_queue_cache_aware_defers_to_front_reinserts():
    """A pool-pressure put-back (``appendleft``) must get the next pop
    VERBATIM: the cache-aware scan is suppressed while a front
    re-insert waits, else a hotter newcomer starves a request the
    batcher already promised to retry."""
    cfg = SchedulerConfig(cache_aware=True, cache_aware_window=8)
    q = AdmissionQueue(cfg)
    score = {0: 0, 1: 7, 2: 1}
    q.prefix_probe = lambda r: score[r.req_id]
    for i in range(3):
        q.append(_Req(i, "t"))
    r = q.popleft()
    assert r.req_id == 1  # hottest jumped the queue
    q.appendleft(r)  # alloc failed: put it back
    score[2] = 99  # a now-hotter rival must NOT displace the put-back
    assert q.popleft().req_id == 1
    assert [q.popleft().req_id, q.popleft().req_id] == [2, 0]


@pytest.mark.parametrize("aware", [True, False])
def test_cache_aware_admission_prefers_resident_prefix(
    clean_slate, batcher_factory, aware
):
    """End-to-end: with ``cache_aware`` on a paged batcher, a queued
    request whose prefix is radix-RESIDENT admits before an
    earlier-arrived cold peer of the same priority (suffix-only
    prefill starts sooner while the pages are still hot); with it off
    the identical traffic stays strict FIFO."""
    rng = np.random.RandomState(31)
    warm = rng.randint(0, 29, size=17).astype(np.int32)  # 2 full pages
    cold = rng.randint(0, 29, size=17).astype(np.int32)
    warm_again = np.concatenate(
        [warm, rng.randint(0, 29, size=5).astype(np.int32)]
    )
    bat = batcher_factory(
        layout="paged", slots=1,
        scheduler=SchedulerConfig(cache_aware=aware),
    )
    bat.submit(warm, 3)
    bat.run()  # retire: warm's full pages stay radix-resident (rc=0)
    first: list[int] = []

    def cb(rid, tok, idx):
        if rid not in first:
            first.append(rid)

    b = bat.submit(cold, 3, on_token=cb)  # arrives first, cold
    c = bat.submit(warm_again, 3, on_token=cb)  # arrives second, warm
    bat.run()
    assert first == ([c, b] if aware else [b, c])


# -- bounded submit ----------------------------------------------------------


def test_submit_rejects_synchronously_and_books_it(
    clean_slate, batcher_factory
):
    bat = batcher_factory(
        slots=1,
        scheduler=SchedulerConfig(
            max_queue_depth=2, preempt=False, degrade=False
        ),
    )
    rng = np.random.RandomState(0)
    accepted = []
    rejections = 0
    for _ in range(6):
        try:
            accepted.append(bat.submit(rng.randint(0, 29, 4), 4))
        except QueueFullError:
            rejections += 1
    # slot admission happens at tick, so at most max_queue_depth
    # requests sit queued; everything past the bound rejected.
    assert rejections == 4
    assert bat.stats()["queued"] <= 2
    assert bat.stats()["rejected"] == 4
    ev = global_flight_recorder().kind_counts()
    assert ev.get("request_rejected") == 4
    c = global_metrics().snapshot()["counters"]
    assert c["scheduler.rejected_total"] == 4
    assert c["scheduler.admitted_total"] == len(accepted)
    # The accepted requests all finish — nothing wedges.
    out = bat.run()
    assert sorted(out) == sorted(accepted)


# -- decode-slot preemption --------------------------------------------------


@pytest.mark.parametrize("layout", ["slots", "paged"])
def test_preemption_bit_identical_and_exactly_once(
    clean_slate, batcher_factory, layout
):
    """The acceptance pin: a preempted request's final stream is
    bit-identical to an unpreempted run of the same request, on both
    layouts, with on_token delivery exactly-once across the
    preemption (stream_skip suppresses the regenerated prefix)."""
    p_low = np.arange(10, dtype=np.int32) % 29
    p_hi = (np.arange(7, dtype=np.int32) * 3) % 29
    # Reference: each request alone on an unpreempted batcher.
    ref = batcher_factory(layout=layout, slots=1)
    r_low = ref.submit(p_low, 20)
    ref_low = ref.run()[r_low]
    r_hi = ref.submit(p_hi, 10)
    ref_hi = ref.run()[r_hi]

    bat = batcher_factory(
        layout=layout,
        slots=1,
        scheduler=SchedulerConfig(
            preempt=True, preempt_ttft_fraction=0.5, degrade=False
        ),
    )
    delivered: dict[int, list] = {}

    def cb(rid, tok, idx):
        delivered.setdefault(rid, []).append((idx, tok))

    low = bat.submit(
        p_low, 20, slo=SLOSpec(tenant="free", priority=0), on_token=cb
    )
    bat.tick()
    bat.tick()  # low decodes a few chunks first
    tokens_before = len(delivered.get(low, []))
    assert tokens_before > 0
    hi = bat.submit(
        p_hi,
        10,
        slo=SLOSpec(ttft_budget_s=1e-4, tenant="gold", priority=10),
        on_token=cb,
    )
    out = bat.run()
    # The preemption fired (tiny TTFT budget: the first tick after the
    # high-priority submit is already past its headroom).
    ev = global_flight_recorder().events("preempted")
    assert len(ev) == 1
    assert ev[0]["data"]["request"] == low
    assert ev[0]["data"]["for_request"] == hi
    assert bat.stats()["preempted"] == 1
    assert global_metrics().snapshot()["counters"][
        "scheduler.preempted_total"
    ] == 1
    # Bit-identity for BOTH parties.
    assert np.array_equal(out[hi], ref_hi)
    assert np.array_equal(out[low], ref_low)
    # Exactly-once delivery: indices 0..n-1 each exactly once, tokens
    # matching the final stream (the regenerated prefix re-ran for
    # state only).
    idxs = [i for i, _ in delivered[low]]
    assert idxs == list(range(len(ref_low)))
    assert [t for _, t in delivered[low]] == list(ref_low)
    if layout == "paged":
        # The victim re-admitted THROUGH the prefix cache: its prompt
        # pages dropped into the LRU at preemption and were shared
        # back on re-admission.
        assert bat.stats()["prefix_hits"] > 0


def test_preemption_fires_on_page_starvation_with_a_free_slot(
    clean_slate, batcher_factory
):
    """A free SLOT is not enough: paged admission is all-or-nothing,
    so a high-priority head whose reservation the pool cannot cover
    (even after evicting every cold page) must still preempt — the
    lower-priority decode's pages are what it is waiting for."""
    bat = batcher_factory(
        layout="paged",
        slots=2,
        pool_pages=10,  # 9 allocatable: low takes 6, gold needs 5
        scheduler=SchedulerConfig(
            preempt=True, preempt_ttft_fraction=0.5, degrade=False
        ),
    )
    rng = np.random.RandomState(7)
    low = bat.submit(
        rng.randint(0, 29, 8), 40,
        slo=SLOSpec(tenant="free", priority=0),
    )
    bat.tick()  # low decoding, 6/9 pages held; one slot FREE
    assert sum(1 for s in bat.slots if s.req is None) == 1
    hi = bat.submit(
        rng.randint(0, 29, 24), 16,
        slo=SLOSpec(ttft_budget_s=1e-4, tenant="gold", priority=10),
    )
    out = bat.run()
    ev = global_flight_recorder().events("preempted")
    assert [e["data"]["request"] for e in ev] == [low]
    assert len(out[hi]) == 16 and len(out[low]) == 40


def test_preemption_picks_lowest_priority_victim_and_spares_equals(
    clean_slate, batcher_factory
):
    bat = batcher_factory(
        slots=2,
        scheduler=SchedulerConfig(
            preempt=True, preempt_ttft_fraction=0.5, degrade=False
        ),
    )
    rng = np.random.RandomState(3)
    mid = bat.submit(
        rng.randint(0, 29, 6), 24, slo=SLOSpec(tenant="m", priority=5)
    )
    low = bat.submit(
        rng.randint(0, 29, 6), 24, slo=SLOSpec(tenant="l", priority=1)
    )
    bat.tick()  # both admitted and decoding
    hi = bat.submit(
        rng.randint(0, 29, 4),
        4,
        slo=SLOSpec(ttft_budget_s=1e-4, tenant="g", priority=9),
    )
    bat.run()
    ev = global_flight_recorder().events("preempted")
    assert [e["data"]["request"] for e in ev] == [low]
    # An equal-or-higher class is never preempted: with only
    # priority-9 slots active, a second priority-9 request waits.
    bat2 = batcher_factory(
        slots=1,
        scheduler=SchedulerConfig(
            preempt=True, preempt_ttft_fraction=0.5, degrade=False
        ),
    )
    a = bat2.submit(
        rng.randint(0, 29, 6), 12,
        slo=SLOSpec(tenant="g", priority=9),
    )
    bat2.tick()
    b = bat2.submit(
        rng.randint(0, 29, 6), 4,
        slo=SLOSpec(ttft_budget_s=1e-4, tenant="g", priority=9),
    )
    out = bat2.run()
    assert not global_flight_recorder().events("preempted")[len(ev):]
    assert len(out[a]) == 12 and len(out[b]) == 4


# -- closed-loop degradation -------------------------------------------------


def test_degradation_ladder_escalates_and_recovers(
    clean_slate, batcher_factory
):
    """Backlog pressure walks the ladder one rung per dwell (draft_k
    shrink -> busy threshold (no disagg attached: no-op rung) ->
    evict cached -> reject best-effort), then de-escalates as the
    queue drains."""
    cfg = SchedulerConfig(
        max_queue_depth=8,
        degrade=True,
        degrade_dwell_s=0.0,
        degrade_occupancy=0.0,  # any occupancy counts as saturated
        degrade_queue_high=0.25,
        degrade_queue_low=0.05,
        preempt=False,
    )
    bat = batcher_factory(
        layout="paged", draft=True, slots=2,
        speculative=SpeculativeConfig(draft_k=4), scheduler=cfg,
    )
    rng = np.random.RandomState(0)
    # Seed a cold cached page: one paged request whose prompt fills a
    # full page, retired before the flood.
    warm = bat.submit(rng.randint(0, 29, 9), 2)
    bat.run()
    assert bat.stats()["pages_cached"] > 0
    # Long-running flood: slots stay occupied and the queue stays
    # above the high watermark across the escalation ticks.
    for _ in range(6):
        bat.submit(rng.randint(0, 29, 4), 30)
    for _ in range(4):
        bat.tick()
    st = bat.stats()
    assert st["degradation_level"] == 4
    assert bat._spec_k_eff == 2  # draft_k 4 -> 4 // 2
    assert bat._queue.shed_best_effort
    assert bat.stats()["pages_cached"] == 0  # cold pages evicted
    with pytest.raises(QueueFullError):
        bat.submit(
            rng.randint(0, 29, 4), 2,
            slo=SLOSpec(tenant="be", priority=-1),
        )
    g = global_metrics().snapshot()
    assert g["counters"]["scheduler.degraded_total"] == 4
    assert g["gauges"]["scheduler.degradation_level"] == 4.0
    ups = [
        e["data"]["step"]
        for e in global_flight_recorder().events("degradation_step")
        if e["data"]["direction"] == "up"
    ]
    assert ups == [
        "draft_k", "busy_threshold", "evict_cached",
        "reject_best_effort",
    ]
    # Drain, then idle ticks de-escalate back to level 0 and restore
    # the configured draft_k.
    bat.run()
    for _ in range(6):
        bat.tick()
    assert bat.stats()["degradation_level"] == 0
    assert bat._spec_k_eff == 4
    assert not bat._queue.shed_best_effort
    assert warm == 0  # the warm request's id (sanity: nothing renumbered)


@pytest.mark.parametrize(
    "sample_kw",
    [
        {},
        # temperature > 0 routes through the speculative-SAMPLING
        # verify (accept/reject + residual resample), but top_k=1
        # shapes the target to a point mass on its argmax — so the
        # committed stream must STILL equal the greedy reference
        # bit-for-bit whatever the ladder does to draft_k mid-serve.
        {"temperature": 0.7, "top_k": 1},
    ],
    ids=["greedy", "sampled_topk1"],
)
def test_shrunk_draft_k_streams_stay_lossless(
    clean_slate, batcher_factory, sample_kw
):
    """set_draft_k mid-serve: the narrowed rounds still commit the
    target's exact stream (losslessness is the target's property, not
    the draft depth's) — in greedy mode AND in sampling mode."""
    p = np.arange(8, dtype=np.int32) % 29
    ref = batcher_factory(slots=1)
    rr = ref.submit(p, 16)
    expect = ref.run()[rr]
    bat = batcher_factory(
        draft=True, slots=1, speculative=SpeculativeConfig(draft_k=4)
    )
    kw = dict(sample_kw)
    if kw:
        kw["rng"] = jax.random.PRNGKey(5)
    r = bat.submit(p, 16, **kw)
    bat.tick()
    bat.set_draft_k(1)  # shrink mid-request
    bat.tick()
    bat.set_draft_k(4)  # and restore
    out = bat.run()
    assert np.array_equal(out[r], expect)


# -- preemption / rejection x disaggregation ---------------------------------


def _build_disagg(scheduler=None, slots=2):
    from adapt_tpu.runtime.disagg import DisaggServer, PrefillWorker

    lm = lm_tiny(vocab=29, max_len=96)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    decode = ContinuousBatcher(
        lm, variables, slots=slots, chunk=4, kv_layout="paged",
        page_size=8, scheduler=scheduler,
    )
    worker = PrefillWorker(
        lm, variables, page_size=8, prefill_chunk=16
    )
    srv = DisaggServer(
        decode, worker,
        DisaggConfig(prompt_threshold=24, busy_prompt_threshold=16),
    )
    return srv, decode, worker


def _assert_partition(pager):
    st = pager.stats()
    assert st.in_use + st.free == pager.num_allocatable
    assert all(rc > 0 for rc in pager._rc.values())


def test_disagg_landing_into_full_queue_fails_only_that_request(
    clean_slate,
):
    """A KV handoff whose decode admission is REJECTED (queue filled
    while the prefill ran) frees the prefill-side pages, leaves the
    adopted decode-side pages rc=0 in the prefix LRU, fails only its
    request, and both pools' page partitions stay exact."""
    srv, decode, worker = _build_disagg(
        scheduler=SchedulerConfig(
            max_queue_depth=2, preempt=False, degrade=False
        ),
        slots=1,
    )
    rng = np.random.RandomState(0)
    long_prompt = rng.randint(0, 29, 40).astype(np.int32)
    sid = srv.submit(long_prompt, 4)  # routed to the prefill tier
    assert srv.disaggregated == 1
    # Fill the decode queue to the bound and pin the one slot with a
    # long decode, so the queue is STILL full when the handoff lands.
    slow = srv.submit(rng.randint(0, 29, 4), 30)
    fillers = [srv.submit(rng.randint(0, 29, 4), 2)]
    srv.tick()  # admits `slow` into the slot; prefill pass 1 runs
    fillers.append(srv.submit(rng.randint(0, 29, 4), 2))
    with pytest.raises(QueueFullError):
        srv.submit(rng.randint(0, 29, 4), 2)  # bound holds for submits
    out = srv.run()
    # The handoff landed (pages adopted) but admission rejected: the
    # request failed cleanly — empty result, not a wedge — and the
    # fillers finished.
    assert out[sid].shape == (0,)
    assert len(out[slow]) == 30
    assert all(len(out[f]) == 2 for f in fillers)
    assert srv.failed == 1
    kinds = global_flight_recorder().kind_counts()
    assert kinds.get("request_failed", 0) == 1
    assert kinds.get("request_rejected", 0) >= 1
    # No leaked rc on either pool; partitions exact. The adopted pages
    # sit rc=0 in the decode LRU (land-then-LRU: evictable capacity,
    # or a free prefix hit for a retry).
    _assert_partition(decode._pager)
    _assert_partition(worker._pager)
    assert worker._pager.stats().in_use == 0
    assert decode._pager.stats().cached > 0
    # A resubmit of the same prompt prefix-hits the adopted pages.
    hits0 = decode._pager.prefix_hits
    sid2 = srv.submit(long_prompt, 4)
    out2 = srv.result(sid2)
    assert len(out2) == 4
    assert decode._pager.prefix_hits > hits0
    decode.close()


@pytest.mark.slow  # two full disagg stacks; the landing-rejection
# test above carries the tier-1 partition pin
def test_preempted_disagg_request_replays_through_adopted_pages(
    clean_slate,
):
    """A disagg-admitted request preempted mid-decode re-queues and
    re-admits through the prefix cache (its prompt pages — adopted at
    landing — went rc=0 into the LRU at preemption); the partition
    stays exact and the stream is bit-identical to an unpreempted
    run."""
    srv, decode, worker = _build_disagg(slots=1)
    rng = np.random.RandomState(1)
    long_prompt = rng.randint(0, 29, 40).astype(np.int32)
    ref_sid = srv.submit(long_prompt, 12)
    expect = srv.result(ref_sid)  # unpreempted reference, same server

    srv2, decode2, worker2 = _build_disagg(
        scheduler=SchedulerConfig(
            preempt=True, preempt_ttft_fraction=0.5, degrade=False
        ),
        slots=1,
    )
    victim = srv2.submit(
        long_prompt, 12, slo=SLOSpec(tenant="free", priority=0)
    )
    # Drive until the disagg request is decoding in its slot.
    for _ in range(40):
        srv2.tick()
        if any(s.req is not None for s in decode2.slots):
            break
    assert any(s.req is not None for s in decode2.slots)
    hi = srv2.submit(
        np.arange(4, dtype=np.int32) % 29,
        4,
        slo=SLOSpec(ttft_budget_s=1e-4, tenant="gold", priority=10),
    )
    out_hi = srv2.result(hi)
    out_victim = srv2.result(victim)
    assert len(out_hi) == 4
    assert np.array_equal(out_victim, expect)
    assert global_flight_recorder().events("preempted")
    _assert_partition(decode2._pager)
    _assert_partition(worker2._pager)
    decode.close()
    decode2.close()


# -- observability ----------------------------------------------------------


def test_scheduler_gauges_and_flight_kinds(clean_slate, batcher_factory):
    bat = batcher_factory(
        slots=1,
        scheduler=SchedulerConfig(
            max_queue_depth=3,
            quotas={"free": TenantQuota(burst=2)},
            preempt=True,
            preempt_ttft_fraction=0.5,
            degrade=True,
            degrade_dwell_s=0.0,
            degrade_occupancy=0.0,
            degrade_queue_high=0.3,
        ),
    )
    rng = np.random.RandomState(0)
    low = bat.submit(
        rng.randint(0, 29, 6), 16,
        slo=SLOSpec(tenant="free", priority=0),
    )
    bat.tick()
    for _ in range(2):
        bat.submit(
            rng.randint(0, 29, 4), 2,
            slo=SLOSpec(tenant="free", priority=0),
        )
    with pytest.raises(QueueFullError):  # burst cap
        bat.submit(
            rng.randint(0, 29, 4), 2,
            slo=SLOSpec(tenant="free", priority=0),
        )
    bat.submit(
        rng.randint(0, 29, 4), 2,
        slo=SLOSpec(ttft_budget_s=1e-4, tenant="gold", priority=5),
    )
    bat.tick()  # preempts low; also degrades (queue high)
    g = global_metrics().snapshot()["gauges"]
    assert "scheduler.queue_depth.free" in g
    assert "scheduler.queue_depth.gold" in g
    bat.run()
    kinds = global_flight_recorder().kind_counts()
    # The satellite contract: every traffic-control lifecycle edge is
    # kind_counts()-visible.
    assert kinds.get("request_rejected", 0) >= 1
    assert kinds.get("preempted", 0) >= 1
    assert kinds.get("degradation_step", 0) >= 1
    c = global_metrics().snapshot()["counters"]
    assert c["scheduler.rejected_total"] >= 1
    assert c["scheduler.preempted_total"] >= 1
    assert c["scheduler.degraded_total"] >= 1
    assert low == 0  # sanity
