"""Pipelined tick runtime (``config.RuntimeConfig``): the depth-2 loop
— dispatch tick *t*, commit tick *t−1* while *t* runs on device — must
be INVISIBLE in outputs. Greedy streams stay bit-identical to the
synchronous ``pipeline_depth=1`` loop on both KV layouts, including
speculative + int8 + tp=2 composed; cancels, preemption and a
kill-mid-stream recovery all land exactly-once with balanced lifecycle
books while the in-flight tick drains at the pipeline boundary; and
the hot-path invariants (0 h2d per steady tick, the two-program
compile footprint) survive the overlapped loop."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from adapt_tpu.config import (
    ParallelConfig,
    RuntimeConfig,
    SchedulerConfig,
    ServeConfig,
    SLOSpec,
    SpeculativeConfig,
)
from adapt_tpu.control.registry import DeviceHealthMonitor
from adapt_tpu.models.transformer_lm import generate, transformer_lm
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.profiling import global_compile_sentinel
from adapt_tpu.utils.tracing import global_flight_recorder


@pytest.fixture(scope="module")
def lm_setup():
    # kv_heads divisible by tp=2 AND tp=4: the same model serves the
    # single-device identity tests, the tp=2 composed test, and the
    # tp=4 -> tp=2 recovery drain test.
    lm = transformer_lm(37, 32, 2, 8, 64, max_len=64, kv_heads=4,
                        name="async_target")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture(scope="module")
def draft_setup():
    draft = transformer_lm(37, 16, 1, 1, 32, max_len=64,
                           name="async_draft")
    variables = draft.graph.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32)
    )
    return draft, variables


def _solo(lm, variables, prompt, steps, **kw):
    return np.asarray(
        generate(lm, variables, jnp.asarray(prompt)[None], steps, **kw)
    )[0]


def _depth(n):
    return RuntimeConfig(pipeline_depth=n)


RNG = np.random.RandomState(11)
PROMPTS = [RNG.randint(0, 37, size=n).astype(np.int32)
           for n in (3, 9, 5, 12, 7)]
STEPS = [20, 4, 8, 3, 6]


def _staggered(bat, cancel_idx=None):
    """Staggered admits + optional mid-flight cancel; returns
    ({idx: tokens}, cancelled_idx_len_ok)."""
    ids = {}
    for i in range(2):
        ids[bat.submit(PROMPTS[i], STEPS[i])] = i
    bat.tick()
    bat.tick()
    for i in range(2, len(PROMPTS)):
        ids[bat.submit(PROMPTS[i], STEPS[i])] = i
    if cancel_idx is not None:
        bat.tick()
        rid = next(r for r, i in ids.items() if i == cancel_idx)
        assert bat.cancel(rid)
    out = bat.run()
    return {ids[r]: out[r] for r in ids}


def test_runtime_config_validation():
    """Depths outside {1, 2} fail eagerly, by name; the ServeConfig
    default is the synchronous loop."""
    assert RuntimeConfig().pipeline_depth == 1
    assert ServeConfig().runtime.pipeline_depth == 1
    for bad in (0, 3, -1):
        with pytest.raises(ValueError, match="pipeline_depth"):
            RuntimeConfig(pipeline_depth=bad)


@pytest.mark.parametrize(
    "layout",
    [
        # Tier-1 budget: the paged variant carries the identity pin
        # (the richer layout — pages, window recycling, prefix cache);
        # the dense-strip variant re-proves the same invariant and
        # rides tier 2 (the composed spec×int8×tp slots variant below
        # is slow-marked for the same reason).
        pytest.param("slots", marks=pytest.mark.slow),
        "paged",
    ],
)
def test_async_bit_identical_staggered(lm_setup, layout):
    """THE identity pin: the same staggered workload (admits,
    retirements, mid-stream EOS-by-steps) under depth 1 and depth 2
    yields bit-identical streams on both layouts, each equal to solo
    generate(); books balance and the pipeline drains empty."""
    lm, variables = lm_setup
    kw = dict(slots=3, chunk=2)
    if layout == "paged":
        kw.update(kv_layout="paged", page_size=8)
    outs = {}
    for depth in (1, 2):
        bat = ContinuousBatcher(
            lm, variables, runtime=_depth(depth), **kw
        )
        outs[depth] = _staggered(bat)
        st = bat.stats()
        assert st["pipeline_depth"] == depth
        assert st["active"] == 0 and st["queued"] == 0
        assert not st["inflight"]  # run() drained the pipeline
        assert st["admitted"] == st["completed"] == len(PROMPTS)
        bat.close()
    for i in range(len(PROMPTS)):
        np.testing.assert_array_equal(
            outs[2][i], outs[1][i], err_msg=f"req {i}: depth2 != depth1"
        )
        np.testing.assert_array_equal(
            outs[2][i], _solo(lm, variables, PROMPTS[i], STEPS[i]),
            err_msg=f"req {i}: depth2 != generate",
        )


def test_async_cancel_mid_flight(lm_setup):
    """A cancel landing while the victim's tick is IN FLIGHT: the
    partial stream is a prefix of solo, on_token stays exactly-once
    and contiguous (no token from the dropped in-flight column leaks),
    and the lifecycle books balance."""
    lm, variables = lm_setup
    got: list[tuple[int, int, int]] = []
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=2, runtime=_depth(2)
    )
    r0 = bat.submit(
        PROMPTS[0], STEPS[0],
        on_token=lambda rid, tok, idx: got.append((rid, tok, idx)),
    )
    r1 = bat.submit(PROMPTS[1], STEPS[1])
    bat.tick()
    bat.tick()  # r0's decode results now ride the one-tick lag
    assert bat.cancel(r0)
    out = bat.run()
    solo = _solo(lm, variables, PROMPTS[0], STEPS[0])
    assert 0 < len(out[r0]) < STEPS[0]
    np.testing.assert_array_equal(out[r0], solo[: len(out[r0])])
    np.testing.assert_array_equal(
        out[r1], _solo(lm, variables, PROMPTS[1], STEPS[1])
    )
    # Exactly-once, contiguous, and consistent with the final result.
    assert [i for (_, _, i) in got] == list(range(len(out[r0])))
    np.testing.assert_array_equal(
        np.asarray([t for (_, t, _) in got], np.int32), out[r0]
    )
    st = bat.stats()
    assert st["admitted"] == st["completed"] == 2
    assert st["active"] == 0 and not st["inflight"]
    bat.close()


def test_async_zero_h2d_and_compile_footprint(lm_setup):
    """The hot-path invariants survive the pipelined loop: steady-state
    depth-2 ticks stage ZERO host arrays, the step-chunk program holds
    ONE compiled variant across churn, and drain() is idempotent."""
    lm, variables = lm_setup
    sentinel = global_compile_sentinel()
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=2, runtime=_depth(2)
    )
    before = sentinel.compiles("continuous.step_chunk")
    r1 = bat.submit(np.asarray([1, 2, 3], np.int32), 30)
    bat.tick()
    bat.tick()
    assert sentinel.compiles("continuous.step_chunk") - before == 1
    h0 = bat.stats()["h2d_transfers"]
    for _ in range(4):
        bat.tick()  # pure steady state, one tick always in flight
    assert bat.stats()["h2d_transfers"] == h0
    assert bat.stats()["inflight"]
    entries = sentinel.compiles("continuous.step_chunk")
    # Churn: retire, re-admit — no variant may be added, and the
    # drained pipeline stays drained (idempotent boundary).
    r2 = bat.submit(np.asarray([5, 6], np.int32), 3)
    out = bat.run()
    assert not bat.stats()["inflight"]
    assert bat.drain() == 0
    r3 = bat.submit(np.asarray([9, 9, 9, 9], np.int32), 5)
    out.update(bat.run())
    assert set(out) == {r1, r2, r3}
    assert sentinel.compiles("continuous.step_chunk") == entries
    bat.close()


@pytest.mark.parametrize("layout", ["paged"])
def test_async_spec_int8_tp2_bit_identical(
    lm_setup, draft_setup, sim_mesh, layout
):
    """The composed pin: speculative + int8 KV + tp=2, depth 1 vs
    depth 2 — streams bit-identical to each other and to solo
    generate(kv_cache_dtype='int8'); exactly ONE verify variant
    compiles per batcher (two-program footprint under the async
    loop)."""
    _async_spec_int8_tp2(lm_setup, draft_setup, sim_mesh, layout)


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["slots"])
def test_async_spec_int8_tp2_bit_identical_slow(
    lm_setup, draft_setup, sim_mesh, layout
):
    """Second layout of the composed pin (slow: tier-1 carries the
    paged variant; the dense-strip layout re-pays the GSPMD compiles
    for the same claim)."""
    _async_spec_int8_tp2(lm_setup, draft_setup, sim_mesh, layout)


def _async_spec_int8_tp2(lm_setup, draft_setup, sim_mesh, layout):
    lm, variables = lm_setup
    draft, dvars = draft_setup
    sentinel = global_compile_sentinel()
    kw = dict(slots=2, kv_cache_dtype="int8", draft_lm=draft,
              draft_variables=dvars,
              speculative=SpeculativeConfig(draft_k=3))
    if layout == "paged":
        kw.update(kv_layout="paged", page_size=8)
    prompts, steps = PROMPTS[:3], [7, 9, 5]
    outs = {}
    for depth in (1, 2):
        bat = ContinuousBatcher(
            lm, variables, mesh=sim_mesh(2),
            parallel=ParallelConfig(tp=2), runtime=_depth(depth), **kw,
        )
        before = sentinel.compiles("continuous.spec_verify")
        ids = {bat.submit(p, s): i
               for i, (p, s) in enumerate(zip(prompts, steps))}
        out = bat.run()
        assert sentinel.compiles("continuous.spec_verify") - before == 1
        assert 0.0 <= bat.stats()["spec_acceptance"] <= 1.0
        outs[depth] = {ids[r]: out[r] for r in ids}
        bat.close()
    for i in range(3):
        np.testing.assert_array_equal(
            outs[2][i], outs[1][i], err_msg=f"req {i}: depth2 != depth1"
        )
        np.testing.assert_array_equal(
            outs[2][i],
            _solo(lm, variables, prompts[i], steps[i],
                  kv_cache_dtype="int8"),
            err_msg=f"req {i}: depth2 != solo int8",
        )


def test_async_preemption_exactly_once(lm_setup):
    """Decode-slot preemption under the one-tick lag: the victim's
    in-flight column is dropped (binding identity), the replayed
    stream stays bit-identical to an unpreempted run, and on_token
    delivery is exactly-once across the preemption."""
    lm, variables = lm_setup
    global_metrics().reset()
    global_flight_recorder().clear()
    p_low, p_hi = PROMPTS[1], PROMPTS[2]
    ref = ContinuousBatcher(
        lm, variables, slots=1, chunk=2, kv_layout="paged", page_size=8
    )
    r = ref.submit(p_low, 20)
    ref_low = ref.run()[r]
    r = ref.submit(p_hi, 10)
    ref_hi = ref.run()[r]
    ref.close()

    bat = ContinuousBatcher(
        lm, variables, slots=1, chunk=2, kv_layout="paged", page_size=8,
        runtime=_depth(2),
        scheduler=SchedulerConfig(
            preempt=True, preempt_ttft_fraction=0.5, degrade=False
        ),
    )
    delivered: dict[int, list] = {}

    def cb(rid, tok, idx):
        delivered.setdefault(rid, []).append((idx, tok))

    low = bat.submit(
        p_low, 20, slo=SLOSpec(tenant="free", priority=0), on_token=cb
    )
    bat.tick()
    bat.tick()
    bat.tick()  # committed tokens exist AND a tick is in flight
    assert len(delivered.get(low, [])) > 0
    hi = bat.submit(
        p_hi, 10,
        slo=SLOSpec(ttft_budget_s=1e-4, tenant="gold", priority=10),
        on_token=cb,
    )
    out = bat.run()
    assert bat.stats()["preempted"] == 1
    assert np.array_equal(out[hi], ref_hi)
    assert np.array_equal(out[low], ref_low)
    for rid, ref_stream in ((low, ref_low), (hi, ref_hi)):
        idxs = [i for i, _ in delivered[rid]]
        assert idxs == list(range(len(ref_stream))), (
            f"req {rid}: duplicated or dropped on_token indices"
        )
        np.testing.assert_array_equal(
            np.asarray([t for _, t in delivered[rid]], np.int32),
            ref_stream,
        )
    st = bat.stats()
    assert st["admitted"] == st["completed"] + st["preempted"] == 3
    assert not st["inflight"]
    bat.close()


def test_async_kill_midstream_recovery_drains_pipeline(
    lm_setup, sim_mesh
):
    """A device kill with a tick IN FLIGHT: recover() drains it at the
    pipeline boundary (its tokens commit, on the old layout) before
    the mesh shrinks tp=4 -> tp=2; surviving requests finish
    bit-identical to solo generate(), on_token stays exactly-once, and
    the books balance with the pipeline empty."""
    lm, variables = lm_setup
    mon = DeviceHealthMonitor()
    bat = ContinuousBatcher(
        lm, variables, mesh=sim_mesh(4), parallel=ParallelConfig(tp=4),
        health=mon, slots=3, chunk=2, kv_layout="paged", page_size=8,
        runtime=_depth(2),
    )
    delivered: dict[int, list] = {}

    def cb(rid, tok, idx):
        delivered.setdefault(rid, []).append((idx, tok))

    steps = [20, 14, 10]
    ids = [
        bat.submit(PROMPTS[i], steps[i], on_token=cb) for i in range(2)
    ]
    bat.tick()
    bat.tick()
    ids.append(bat.submit(PROMPTS[2], steps[2], on_token=cb))
    bat.tick()  # all three slot-bound; one tick in flight
    assert bat.stats()["inflight"]
    mon.kill(list(bat._mesh.devices.flat)[3])
    out = bat.run()
    st = bat.stats()
    assert st["tp"] == 2
    assert st["recoveries"] == 1
    assert st["active"] == 0 and not st["inflight"]
    assert st["admitted"] == 3
    assert st["completed"] + st["recovery_dropped"] == 3
    for i, rid in enumerate(ids):
        solo = _solo(lm, variables, PROMPTS[i], steps[i])
        np.testing.assert_array_equal(
            out[rid], solo, err_msg=f"req {i}: killed != solo"
        )
        idxs = [j for j, _ in delivered[rid]]
        assert idxs == list(range(len(solo))), (
            f"req {i}: duplicated or dropped on_token across recovery"
        )
        np.testing.assert_array_equal(
            np.asarray([t for _, t in delivered[rid]], np.int32), solo
        )
    bat.close()
