"""Capacity / placement-signal plane (``runtime/capacity``).

The contract stack, bottom-up: the ``TTFTForecaster``'s EWMA + bias
calibration math (snap-down from compile-scale outliers, bucket
fallbacks, within-2x verdicts), the bounded prefix-affinity sketch and
its static ``affinity_score`` (ranking a prefix-resident replica above
a cold one from hashed sketches alone), ``HealthScore``'s
worsen-fast/improve-slow hysteresis, and the full ``CapacityModel``
book riding a real paged ``ContinuousBatcher`` — headroom partition
reconciled against ``Pager.stats``, submit-time forecasts landing on
requests, and the book staying JSON-safe."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from adapt_tpu.config import CapacityConfig
from adapt_tpu.models.transformer_lm import lm_tiny
from adapt_tpu.runtime.capacity import (
    BOOK_V,
    HealthScore,
    TTFTForecaster,
    affinity_score,
    sketch_from_pager,
    stage_book,
)
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.runtime.paged import Pager


# -- forecaster --------------------------------------------------------------


def test_forecaster_cold_returns_zero_and_learns_additively():
    f = TTFTForecaster(alpha=0.5)
    assert f.forecast(32) == 0.0  # nothing learned: no estimate
    f.observe_queue_wait(0.010)
    f.observe_prefill(32, 0.020)
    f.observe_tick_gap(0.005)
    # bias starts at 1.0: the forecast is the sum of the three terms.
    assert abs(f.forecast(32) - 0.035) < 1e-9
    # A prefix hit shrinks the suffix into a different bucket.
    assert f.forecast(32, prefix_hit_tokens=32) < f.forecast(32)


def test_forecaster_bucket_fallbacks():
    f = TTFTForecaster()
    f.observe_prefill(16, 0.016)
    # Unseen bucket: nearest learned bucket scaled by the token ratio.
    assert abs(f._wall_for(64) - 0.016 * 4) < 1e-9
    assert abs(f._wall_for(4) - 0.016 / 4) < 1e-9
    # Empty walls entirely: per-token EWMA fallback.
    g = TTFTForecaster()
    assert g._wall_for(8) == 0.0
    g._per_token = 0.001
    assert abs(g._wall_for(8) - 0.008) < 1e-9


def test_forecaster_snaps_down_from_compile_scale_walls():
    """A wall 4x under the EWMA replaces it outright: warmup
    admissions measure jit compiles through the same host sync as real
    walls, and the steady state must not take 1/alpha admissions to
    recover (the capacity_smoke train/measure protocol relies on
    this)."""
    f = TTFTForecaster(alpha=0.2)
    f.observe_prefill(8, 2.0)  # compile-inflated
    f.observe_prefill(8, 0.002)  # first real wall
    assert f._walls[8] == 0.002  # snapped, not 2.0 * 0.8 + ...
    # Upward moves still decay (one slow tick must not own the EWMA).
    f.observe_prefill(8, 0.004)
    assert 0.002 < f._walls[8] < 0.003
    f.observe_queue_wait(1.0)
    f.observe_queue_wait(0.001)
    assert f._queue_wait == 0.001


def test_forecaster_calibration_window_and_bias():
    f = TTFTForecaster(alpha=0.2, window=8)
    assert f.calibration() == 1.0  # empty window: unproven, not failing
    assert f.record_realized(0.010, 0.012) is True  # within 2x
    assert f.record_realized(0.010, 0.050) is False  # 5x out
    assert f.calibration() == 0.5
    # Ignored pairs (no forecast / no realized) never enter the books.
    assert f.record_realized(0.0, 0.01) is False
    assert f.record_realized(0.01, 0.0) is False
    assert f.calibration() == 0.5
    # Systematic 4x under-forecast drives the bias corrector UP until
    # forecasts land within 2x.
    f.observe_queue_wait(0.005)
    for _ in range(40):
        f.record_realized(f.forecast(4), 4 * 0.005)
    assert f.forecast(4) > 2 * 0.005
    assert f._bias > 1.0
    # reset_calibration drops only the verdicts: walls + bias survive.
    bias = f._bias
    f.reset_calibration()
    assert f.calibration() == 1.0 and f._bias == bias
    assert f._queue_wait == 0.005
    snap = f.snapshot()
    assert snap["samples"] > 0 and json.loads(json.dumps(snap)) == snap


# -- affinity sketch ---------------------------------------------------------


def _registered_pager(prompts, P=4, num_pages=32):
    """A pager with each prompt's full shareable page run registered
    (the admission-side path, minus the batcher)."""
    p = Pager(num_pages=num_pages, slots=4, pages_per_slot=8, page_tokens=P)
    for slot, toks in enumerate(prompts):
        toks = np.asarray(toks, np.int32)
        pages = (len(toks) - 1) // P
        assert p.alloc(slot, pages)
        for j, page in enumerate(p.owned(slot)[:pages]):
            p.register(page, Pager.prefix_key(toks, (j + 1) * P))
    return p


def test_sketch_bounded_and_affinity_ranks_resident_over_cold():
    P = 4
    hot = np.arange(100, 117, dtype=np.int32)  # 4 shareable pages
    resident = _registered_pager([hot], P=P)
    sk = sketch_from_pager(resident, k=32)
    assert sk["v"] == BOOK_V and sk["page_tokens"] == P
    assert len(sk["entries"]) == 4
    # Hashed content keys only: no raw tokens leave the replica.
    assert all(set(e) == {"h", "d", "t", "heat"} for e in sk["entries"])
    probe = np.concatenate([hot, [1, 2, 3]]).astype(np.int32)
    score = affinity_score(sk, probe)
    assert score >= 16.0  # all four pages matched, token-weighted
    cold = sketch_from_pager(
        Pager(num_pages=32, slots=4, pages_per_slot=8, page_tokens=P), k=32
    )
    assert affinity_score(cold, probe) == 0.0
    assert score > affinity_score(cold, probe)
    # An unrelated prompt scores cold on the resident sketch too.
    assert affinity_score(sk, np.arange(900, 917, dtype=np.int32)) == 0.0
    # Malformed / versioned-away sketches degrade to 0.0, never raise.
    assert affinity_score({"v": 99}, probe) == 0.0
    assert affinity_score({}, probe) == 0.0


def test_sketch_top_k_eviction_prefers_deep_paths():
    P = 4
    deep = np.arange(50, 63, dtype=np.int32)  # 3-page path
    churn = [
        np.arange(1000 + 10 * i, 1000 + 10 * i + 5, dtype=np.int32)
        for i in range(3)  # depth-1 noise
    ]
    p = _registered_pager([deep] + churn, P=P)
    sk = sketch_from_pager(p, k=2)
    assert len(sk["entries"]) <= 2
    # Weight = depth * (1 + hits): the deep path's nodes out-rank the
    # shallow churn, so the bounded sketch still scores the deep probe.
    probe = np.concatenate([deep, [7, 7, 7]]).astype(np.int32)
    assert affinity_score(sk, probe) >= 2 * P


# -- health hysteresis -------------------------------------------------------


def test_health_worsens_fast_improves_after_dwell():
    h = HealthScore(dwell_s=1.0)
    assert h.level == 0 and h.name == "ok"
    assert h.update(2, now=10.0) == 2  # worsening applies immediately
    assert h.name == "critical"
    assert h.update(0, now=10.5) == 2  # improvement pending, in dwell
    assert h.update(0, now=10.9) == 2
    assert h.update(1, now=11.0) == 2  # candidate changed: dwell restarts
    assert h.update(1, now=11.9) == 2
    assert h.update(1, now=12.1) == 1  # held 1.1s >= dwell: published
    assert h.update(2, now=12.2) == 2  # re-worsen is instant again
    # A worsening mid-dwell cancels the pending improvement.
    assert h.update(0, now=13.0) == 2
    assert h.update(2, now=13.5) == 2
    assert h.update(0, now=14.2) == 2  # dwell restarted at 14.2, not 13.0
    assert h.update(0, now=15.3) == 0


# -- the full book on a live paged batcher -----------------------------------


def test_capacity_book_on_paged_batcher_reconciles_headroom():
    lm = lm_tiny(vocab=31, max_len=96)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4,
        kv_layout="paged", page_size=8,
        capacity=CapacityConfig(refresh_s=0.0),
    )
    cap = bat._capacity
    assert cap is not None
    rng = np.random.RandomState(0)
    bat.submit(rng.randint(1, 31, size=18).astype(np.int32), 12)
    for _ in range(5):
        bat.tick()
    book = cap.refresh_book(bat)
    hr = book["headroom"]
    assert book["v"] == BOOK_V and book["kind"] == "decode"
    assert hr["slots_total"] == 2
    assert hr["slots_free"] == sum(
        1 for s in bat.slots if s.req is None
    )
    ps = bat._pager.stats()
    assert hr["pages_total"] == ps.num_pages
    assert hr["pages_in_use"] == ps.in_use and hr["pages_free"] == ps.free
    # Pager partition: page 0 is the never-allocated trash page, and
    # "free" counts the evictable cache (cached <= free).
    assert hr["pages_free"] + hr["pages_in_use"] == hr["pages_total"] - 1
    assert hr["pages_cached"] <= hr["pages_free"]
    assert 0.0 <= hr["queue_frac"] <= 1.0
    assert json.loads(json.dumps(book)) == book  # wire-safe
    # The first admission trained the forecaster through the live
    # _admit seam: a second submit carries a positive forecast.
    rid = bat.submit(rng.randint(1, 31, size=10).astype(np.int32), 4)
    req = next(r for r in bat._queue if r.req_id == rid)
    assert req.ttft_forecast_s > 0.0
    bat.run()
    bat.tick()  # idle flush: pending (forecast, realized) pairs drain
    assert cap.forecaster._samples >= 1
    assert 0.0 <= cap.calibration() <= 1.0
    assert bat.capacity_book()["forecast"]["samples"] >= 1
    bat.close()


def test_capacity_disabled_attaches_nothing():
    lm = lm_tiny(vocab=31, max_len=64)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4,
        capacity=CapacityConfig(enabled=False),
    )
    assert bat._capacity is None and bat.capacity_book() is None
    bat.submit(np.arange(1, 7, dtype=np.int32), 4)
    bat.run()  # the gated sites are all no-ops end to end
    bat.close()


def test_stage_book_shape():
    b = stage_book(3, backlog=2)
    assert b["v"] == BOOK_V and b["kind"] == "stage"
    assert b["headroom"] == {"stages": 3, "backlog": 2}
    assert json.loads(json.dumps(b)) == b
