"""Sequence-parallel long-context prefill (ISSUE 15): ring attention
at serving shapes, and the sp-sharded prefill path end to end.

Pinned contracts:

- **Ring parity at serving shapes**: ``parallel.ring_attention`` vs
  the dense oracle in ``ops/attention`` — GQA head counts (repeated
  KV), causal masking whose live/dead split spans multiple ring
  steps, and the odd-last-chunk recipe (pad to the ring size under a
  causal mask, slice the real prefix).
- **Byte-equality**: ``parallel.sp_prefill.SPPrefiller`` pages equal
  the single-device chunked prefill's pages BIT FOR BIT — native,
  int8 and int4 pools, sp in {2, 4}, GQA + rope models, and the
  sp x tp composed mesh against the tp-sharded chunked prefill (tp
  math is compared at matched tp, the PR-5 discipline).
- **Serving**: greedy streams through an sp-enabled batcher are
  bit-identical to the plain batcher's; admissions land through the
  prefix cache (suffix-only pass); steady decode ticks stay at ZERO
  h2d transfers; the disagg tier's sp dispatch serves prompts whose
  pages exceed its pool.
- **Recovery**: killing a device shared by the decode mesh and the
  sp ring re-shards the batcher AND rebuilds the prefiller on
  surviving devices; streams stay bit-identical and later long
  admissions still take the sp path.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from adapt_tpu.config import ParallelConfig, PrefillConfig
from adapt_tpu.models.transformer_lm import transformer_lm
from adapt_tpu.parallel.ring_attention import full_attention, ring_attention
from adapt_tpu.parallel.sp_prefill import (
    SPPrefiller,
    build_sp_mesh,
    ring_collect,
)
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.runtime.disagg import DisaggServer, PrefillWorker
from adapt_tpu.config import DisaggConfig

VOCAB = 61
PAGE = 8


@pytest.fixture(scope="module")
def lm_setup():
    lm = transformer_lm(VOCAB, 32, 2, 2, 64, max_len=96, name="sp_lm")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture(scope="module")
def gqa_lm_setup():
    # GQA (4 query heads sharing 2 KV heads) + rope: the serving-shape
    # composition the ring/sp paths must keep exact.
    lm = transformer_lm(
        VOCAB, 32, 2, 4, 64, max_len=96, kv_heads=2, pos="rope",
        name="sp_gqa_lm",
    )
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


def _worker_pages(lm, variables, prompt, dtype, chunk=PAGE, tag=""):
    w = PrefillWorker(
        lm, variables, page_size=PAGE, prefill_chunk=chunk,
        kv_cache_dtype=dtype, name=f"ref{tag}{dtype}",
    )
    w.submit(1, prompt)
    outs = []
    while not outs:
        outs = w.step()
    return outs[0].blocks


def _assert_tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- ring attention parity at serving shapes (satellite) -------------------


def _rand_qkv(rng, b, h, s, d, kv_heads=None):
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    kvh = kv_heads or h
    k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    if kvh != h:
        # Adjacent-block repeat — the GQA convention (_repeat_kv).
        k = jnp.repeat(k, h // kvh, axis=1)
        v = jnp.repeat(v, h // kvh, axis=1)
    return q, k, v


@pytest.mark.parametrize("kv_heads", [None, 2, 1])
def test_ring_attention_gqa_serving_shapes(sim_mesh, kv_heads):
    """Ring attention matches the dense oracle at GQA head counts
    (repeated KV per the model convention) — causal and full."""
    mesh = sim_mesh(4, axis="sp")
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng, 1, 4, 32, 16, kv_heads)
    for causal in (False, True):
        out = ring_attention(q, k, v, mesh, axis="sp", causal=causal)
        ref = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )


def test_ring_attention_causal_across_ring_steps(sim_mesh):
    """Causal masking stays exact when the live/dead boundary crosses
    several ring steps (8 ranks, 5 tokens per shard)."""
    mesh = sim_mesh(8, axis="sp")
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng, 2, 2, 40, 8)
    out = ring_attention(q, k, v, mesh, axis="sp", causal=True)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ring_attention_odd_last_chunk(sim_mesh):
    """A sequence that does not divide the ring raises by name, and
    the documented recipe — pad to the ring size, run CAUSAL, slice
    the real prefix — matches the unpadded oracle (padded keys sit at
    positions after every real query, so the causal mask removes
    them)."""
    mesh = sim_mesh(4, axis="sp")
    rng = np.random.default_rng(2)
    s = 27  # odd last chunk: 27 = 3 full 8-token shards + 3
    q, k, v = _rand_qkv(rng, 1, 2, s, 8)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh, axis="sp", causal=True)
    pad = (-s) % 4
    pq, pk, pv = (
        jnp.pad(t, ((0, 0), (0, 0), (0, pad), (0, 0)))
        for t in (q, k, v)
    )
    out = ring_attention(pq, pk, pv, mesh, axis="sp", causal=True)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out)[:, :, :s], np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ring_attention_flash_block_parity(sim_mesh):
    """The streaming-kernel per-device block (``block_impl="flash"``,
    Pallas in interpreter mode on CPU) merges by logsumexp to the same
    result as the dense oracle at serving shapes — contiguous and
    striped causal layouts."""
    mesh = sim_mesh(2, axis="sp")
    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, 1, 2, 32, 16)
    ref = full_attention(q, k, v, causal=True)
    out = ring_attention(
        q, k, v, mesh, axis="sp", causal=True, block_impl="flash"
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ring_collect_is_exact_concatenation(sim_mesh):
    """The sp path's ring transport: P-1 ppermute hops reassemble the
    full window bit-exactly on every rank."""
    mesh = sim_mesh(4, axis="sp")
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((1, 2, 32, 8)), jnp.float32)
    out = ring_collect(x, mesh, "sp", seq_dim=2)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


# -- sp prefill byte-equality ----------------------------------------------


@pytest.mark.parametrize(
    "dtype",
    [
        "native",
        "int8",
        # int4 rides the same quantize-then-ring path; slow-marked to
        # keep the tier-1 window lean (native + int8 are the
        # acceptance pins).
        pytest.param("int4", marks=pytest.mark.slow),
    ],
)
def test_sp_pages_byte_equal_chunked_prefill(lm_setup, sim_mesh, dtype):
    """The tentpole pin: sp-prefilled pages are byte-equal to the
    single-device chunked prefill's — native, int8 and packed-int4
    pools, sp=2 (and sp=4 on the native arm)."""
    lm, variables = lm_setup
    prompt = np.random.default_rng(7).integers(
        1, VOCAB, size=41
    ).astype(np.int32)
    ref = _worker_pages(lm, variables, prompt, dtype, tag="a")
    for sp in (2, 4) if dtype == "native" else (2,):
        pf = SPPrefiller(
            lm, variables, build_sp_mesh(sp), PAGE,
            kv_cache_dtype=dtype, name=f"t{sp}{dtype}",
        )
        m, blocks = pf.prefill(prompt)
        assert m == 5
        _assert_tree_equal(ref, blocks)
        pf.close()


def test_sp_pages_byte_equal_gqa_rope(gqa_lm_setup, sim_mesh):
    """GQA + rope at sp=2: the grouped-query fold and the rotary
    positions survive the sequence split bit-exactly."""
    lm, variables = gqa_lm_setup
    prompt = np.random.default_rng(8).integers(
        1, VOCAB, size=37
    ).astype(np.int32)
    ref = _worker_pages(lm, variables, prompt, "native", tag="g")
    pf = SPPrefiller(
        lm, variables, build_sp_mesh(2), PAGE, name="tg",
    )
    m, blocks = pf.prefill(prompt)
    assert m == 4
    _assert_tree_equal(ref, blocks)
    pf.close()


def test_sp_tp_composed_pages_byte_equal(lm_setup, sim_mesh):
    """sp x tp composition: a (sp=2, tp=2) prefiller's pages equal the
    tp=2 batcher's OWN chunked prefill bit for bit (tp math compares
    at matched tp — the PR-5 discipline; tp=2 vs tp=1 was never
    bitwise, only stream-identical)."""
    lm, variables = lm_setup
    mesh = sim_mesh(2, axis="tp")
    prompt = np.random.default_rng(9).integers(
        1, VOCAB, size=41
    ).astype(np.int32)
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged",
        page_size=PAGE, prefill_chunk=PAGE, mesh=mesh,
        parallel=ParallelConfig(tp=2),
    )
    bat.submit(prompt, 8)
    for _ in range(8):
        bat.tick()
        if bat.slots[0].req is not None and bat.slots[0].pf_done < 0:
            break
    owned = bat._pager.owned(0)[:5]
    ref = [
        jax.tree.map(
            lambda pool: np.asarray(pool[np.asarray(owned)]), pair
        )
        for pair in bat._caches
    ]
    pf = SPPrefiller(
        lm, variables, build_sp_mesh(2, 2), PAGE, tp_axis="tp",
        name="ttp",
    )
    m, blocks = pf.prefill(prompt)
    assert m == 5
    _assert_tree_equal(ref, blocks)
    pf.close()
    bat.close()


# -- serving end to end ----------------------------------------------------


def _run_streams(lm, variables, prompts, steps, **kw):
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged",
        page_size=PAGE, prefill_chunk=2 * PAGE, **kw,
    )
    rids = [bat.submit(p, steps) for p in prompts]
    outs = bat.run()
    streams = [outs[r] for r in rids]
    return bat, streams


def test_sp_batcher_streams_bit_identical(lm_setup, sim_mesh):
    """Greedy streams through the sp-enabled batcher equal the plain
    batcher token for token; long admissions take the sp path and
    land as prefix hits; steady decode ticks stay at zero h2d."""
    lm, variables = lm_setup
    rng = np.random.default_rng(3)
    prompts = [
        rng.integers(1, VOCAB, size=n).astype(np.int32)
        for n in (41, 7, 33, 25)
    ]
    ref_bat, ref = _run_streams(lm, variables, prompts, 8)
    ref_bat.close()
    bat, got = _run_streams(
        lm, variables, prompts, 8,
        prefill=PrefillConfig(sp_threshold=24, sp_width=2),
    )
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)
    st = bat.stats()
    assert st["sp_prefills"] == 3  # 41, 33, 25 >= threshold 24
    assert st["sp_width"] == 2
    # The sp landings are prefix hits (suffix-only admission).
    assert st["prefix_hits"] >= 3
    # Steady-state decode ticks stage nothing after an sp admission.
    rid = bat.submit(prompts[0], 24)  # re-admit: full prefix hit
    bat.tick()
    h2d0 = bat.stats()["h2d_transfers"]
    for _ in range(2):
        bat.tick()
    assert bat.stats()["h2d_transfers"] == h2d0
    bat.run()
    bat.close()


def test_sp_requires_paged_layout(lm_setup, sim_mesh):
    lm, variables = lm_setup
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(
            lm, variables, slots=2, kv_layout="slots",
            prefill=PrefillConfig(sp_threshold=24, sp_width=2),
        )


def test_prefill_config_validation():
    with pytest.raises(ValueError, match="power of two"):
        PrefillConfig(sp_threshold=8, sp_width=3)
    with pytest.raises(ValueError, match="sp_threshold"):
        PrefillConfig(sp_threshold=0, sp_width=2)
    assert not PrefillConfig().enabled
    assert not PrefillConfig(sp_threshold=8, sp_width=1).enabled
    assert PrefillConfig(sp_threshold=8, sp_width=2).enabled


def test_sp_mesh_tp_mismatch_raises(lm_setup, sim_mesh):
    """A tp=2 batcher refuses an sp mesh without its tp axis — sp
    pages must be what ITS tp-sharded prefill would write."""
    lm, variables = lm_setup
    mesh = sim_mesh(2, axis="tp")
    with pytest.raises(ValueError, match="tp axis"):
        ContinuousBatcher(
            lm, variables, slots=2, kv_layout="paged", page_size=PAGE,
            mesh=mesh, parallel=ParallelConfig(tp=2),
            prefill=PrefillConfig(sp_threshold=24, sp_width=2),
            sp_mesh=build_sp_mesh(2),  # sp-only: no tp axis
        )


def test_disagg_sp_serves_past_pool_capacity(lm_setup, sim_mesh):
    """The prefill tier's sp dispatch: prompts whose full pages exceed
    the worker pool disaggregate anyway (the sp program holds the span
    sp-sharded, never in the pool) and stream bit-identically to the
    collocated reference."""
    lm, variables = lm_setup
    rng = np.random.default_rng(5)
    prompts = [
        rng.integers(1, VOCAB, size=n).astype(np.int32)
        for n in (41, 7, 60)
    ]

    def run(sp_cfg, tag):
        decode = ContinuousBatcher(
            lm, variables, slots=2, chunk=4, kv_layout="paged",
            page_size=PAGE,
        )
        worker = PrefillWorker(
            lm, variables, page_size=PAGE, prefill_chunk=2 * PAGE,
            pool_pages=3, name=f"w{tag}", prefill=sp_cfg,
        )
        srv = DisaggServer(
            decode, worker,
            DisaggConfig(prompt_threshold=24, busy_prompt_threshold=24),
        )
        sids = [srv.submit(p, 8) for p in prompts]
        outs = srv.run()
        st = worker.stats()
        srv.close()
        decode.close()
        return [outs[s] for s in sids], st

    # Pool of 2 allocatable pages: without sp the 41/60-token prompts
    # CANNOT disaggregate (placement falls back collocated).
    ref, st0 = run(None, "off")
    assert st0["handoffs"] == 0
    got, st1 = run(PrefillConfig(sp_threshold=24, sp_width=2), "on")
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)
    assert st1["sp_prefills"] == 2
    assert st1["handoffs"] == 2


@pytest.mark.slow
def test_sp_recovery_rebuilds_ring(lm_setup, sim_mesh):
    """Kill a device shared by the tp=2 decode mesh and the
    (sp=2, tp=2) ring mid-stream: the batcher re-shards to tp=1,
    the prefiller rebuilds on surviving devices, migrated streams
    stay bit-identical, and a LATER long admission still takes the
    sp path on the rebuilt ring."""
    from adapt_tpu.control.registry import DeviceHealthMonitor

    lm, variables = lm_setup
    mesh = sim_mesh(2, axis="tp")
    rng = np.random.default_rng(11)
    prompts = [
        rng.integers(1, VOCAB, size=n).astype(np.int32)
        for n in (41, 33)
    ]
    # Uninterrupted reference (plain batcher, no sp, no mesh).
    ref_bat, ref = _run_streams(lm, variables, prompts, 12)
    ref_bat.close()

    health = DeviceHealthMonitor()
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged",
        page_size=PAGE, prefill_chunk=2 * PAGE, mesh=mesh,
        parallel=ParallelConfig(tp=2), health=health,
        prefill=PrefillConfig(sp_threshold=24, sp_width=2),
        sp_mesh=build_sp_mesh(2, 2),
    )
    rids = [bat.submit(p, 12) for p in prompts]
    for _ in range(2):
        bat.tick()
    assert bat.stats()["sp_prefills"] == 2
    victim = list(mesh.devices.flat)[1]
    health.kill(victim)
    outs = bat.run()
    st = bat.stats()
    assert st["tp"] == 1
    assert st["recoveries"] == 1
    for rid, want in zip(rids, ref):
        np.testing.assert_array_equal(outs[rid], want)
    # The rebuilt ring still sp-prefills fresh long admissions.
    assert st["sp_width"] == 2
    p_new = rng.integers(1, VOCAB, size=39).astype(np.int32)
    rid = bat.submit(p_new, 8)
    got = bat.run()[rid]
    assert bat.stats()["sp_prefills"] == 3
    solo_bat, solo = _run_streams(lm, variables, [p_new], 8)
    solo_bat.close()
    np.testing.assert_array_equal(got, solo[0])
    bat.close()
