"""Fleet router: prefix-affinity placement, resharded handoff wire,
lease-driven membership, autoscaling.

What is verified here:

- the head-tiled wire: ``pack_handoff(head_ranges=...)`` frames each
  KV leaf as one contiguous slice per destination shard and
  ``unpack_handoff`` regroups them bit-exactly (sender-side reshard —
  never a global gather);
- ``FleetRouter`` placement streams bit-identical to a single-replica
  reference (routing is a placement property, never a numerics one);
- the cross-replica disagg path: a prefill tier feeding a tp=4 decode
  replica over the real wire, 4 head tiles per leaf, landing through
  ``adopt_prefill_pages`` as an ordinary prefix hit;
- the kill-one-of-3 acceptance: deregister one replica's lease
  mid-load, the router re-places its work within the recovery budget,
  streams stay bit-identical and token delivery exactly-once;
- synchronous shed through the replicas' admission books, the
  autoscaler's up/down edges, ``FederatedStore``'s capacity-book
  max-age evict, the ``/fleet/placements`` endpoint and the
  ``fleet_top`` rendering that consumes it.
"""

import importlib.util
import json
import pathlib
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.comm.framing import frame_parts, parse_frame
from adapt_tpu.config import (
    CapacityConfig,
    DisaggConfig,
    ParallelConfig,
    RouterConfig,
    SchedulerConfig,
    ServeConfig,
)
from adapt_tpu.control.registry import WorkerRegistry
from adapt_tpu.models.transformer_lm import transformer_lm
from adapt_tpu.parallel.sharding import head_tiles
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.runtime.disagg import (
    HandoffError,
    KVHandoff,
    PrefillWorker,
    pack_handoff,
    unpack_handoff,
)
from adapt_tpu.runtime.router import FleetAutoscaler, FleetRouter
from adapt_tpu.runtime.scheduler import QueueFullError
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.telemetry import FederatedStore
from adapt_tpu.utils.tracing import global_flight_recorder

VOCAB = 31
PAGE = 8


@pytest.fixture
def clean_slate():
    global_metrics().reset()
    global_flight_recorder().clear()
    yield
    global_metrics().reset()
    global_flight_recorder().clear()


@pytest.fixture(scope="module")
def lm_setup():
    # heads=4 so a tp=4 decode replica is buildable (and kv head
    # tiling by 4 engages on the wire); small everywhere else —
    # every batcher compiles its own programs and tier-1 wall time
    # is the budget.
    lm = transformer_lm(VOCAB, 32, 2, 4, 64, max_len=96,
                        name="router_lm")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


def _mk_replica(lm, variables, mesh=None, tp=1, scheduler=None):
    kw = dict(
        slots=2, chunk=PAGE, kv_layout="paged", page_size=PAGE,
        capacity=CapacityConfig(refresh_s=0.0), scheduler=scheduler,
    )
    if mesh is not None:
        kw.update(mesh=mesh, parallel=ParallelConfig(tp=tp))
    return ContinuousBatcher(lm, variables, **kw)


# -- config ------------------------------------------------------------------


def test_router_config_validation():
    assert ServeConfig().router.policy == "affinity"
    with pytest.raises(ValueError, match="policy"):
        RouterConfig(policy="round_robin")
    with pytest.raises(ValueError, match="max_replicas"):
        RouterConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="scale_up_queue_frac"):
        RouterConfig(scale_up_queue_frac=1.5)
    with pytest.raises(ValueError, match="book_max_age_s"):
        RouterConfig(book_max_age_s=0.0)


# -- the resharded wire ------------------------------------------------------


def test_head_tiles():
    assert head_tiles(8, 4) == [(0, 2), (2, 4), (4, 6), (6, 8)]
    assert head_tiles(4, 1) == [(0, 4)]
    with pytest.raises(ValueError):
        head_tiles(3, 2)  # heads must tile evenly
    with pytest.raises(ValueError):
        head_tiles(4, 0)


def _rand_handoff(rng, quantized=False, blocks=2, n=3, kvh=4, hd=4):
    def member():
        if quantized:
            return (
                rng.randint(-127, 127, size=(n, kvh, PAGE, hd)).astype(
                    np.int8
                ),
                rng.rand(n, kvh, PAGE, 1).astype(np.float32),
            )
        return rng.rand(n, kvh, PAGE, hd).astype(np.float32)

    return KVHandoff(
        req_id=7,
        prompt=rng.randint(0, VOCAB, size=n * PAGE + 3).astype(np.int32),
        page_size=PAGE,
        n_pages=n,
        quantized=quantized,
        blocks=[(member(), member()) for _ in range(blocks)],
    )


@pytest.mark.parametrize("quantized", [False, True])
def test_ranged_handoff_wire_roundtrip(quantized):
    """Sender-side reshard on the wire: with ``head_ranges`` every KV
    leaf ships as one contiguous frame PER destination tile (the annex
    records the tiling, frame count grows to 1 + leaves * R), and the
    receive side regroups tiles host-side bit-exactly — the resharded
    wire and today's whole-leaf wire decode to the same handoff."""
    rng = np.random.RandomState(3)
    h = _rand_handoff(rng, quantized=quantized, kvh=4)
    ranges = head_tiles(4, 2)
    msg = pack_handoff(h, head_ranges=ranges)
    meta = json.loads(msg.page_annex.decode())
    assert meta["head_ranges"] == [[0, 2], [2, 4]]
    leaves = 2 * 2 * (2 if quantized else 1)  # blocks * (K,V) * planes
    assert len(meta["frame_lens"]) == 1 + leaves * 2
    wire = bytearray(b"".join(frame_parts(msg)))
    got = unpack_handoff(parse_frame(memoryview(wire)[8:]))
    assert got.n_pages == h.n_pages and got.quantized == quantized
    np.testing.assert_array_equal(got.prompt, h.prompt)
    for (hk, hv), (gk, gv) in zip(h.blocks, got.blocks):
        if quantized:
            for (a, b), (c, d) in ((hk, gk), (hv, gv)):
                np.testing.assert_array_equal(a, c)
                np.testing.assert_array_equal(b, d)
        else:
            np.testing.assert_array_equal(hk, gk)
            np.testing.assert_array_equal(hv, gv)


def test_ranged_handoff_bad_tiling_raises():
    rng = np.random.RandomState(4)
    h = _rand_handoff(rng, kvh=4)
    with pytest.raises(HandoffError, match="head_ranges"):
        pack_handoff(h, head_ranges=[(0, 2)])  # leaves heads 2..4 behind
    with pytest.raises(HandoffError, match="head_ranges"):
        pack_handoff(h, head_ranges=[(0, 3), (2, 4)])  # overlap


# -- placement ---------------------------------------------------------------


def test_router_placement_bit_identical(clean_slate, lm_setup):
    """Two replicas behind the router: every stream is bit-identical
    to a single-replica reference (placement is a scheduling decision,
    not a numerics one), the decision ring explains each landing and
    the router's books balance."""
    lm, variables = lm_setup
    reg = WorkerRegistry()
    router = FleetRouter(
        {"r0": _mk_replica(lm, variables),
         "r1": _mk_replica(lm, variables)},
        registry=reg,
    )
    rng = np.random.RandomState(0)
    toks = {}
    prompts, sids = [], []
    for i in range(6):
        p = rng.randint(1, VOCAB, size=12 + (i % 3) * 8).astype(np.int32)
        sid = router.submit(
            p, steps=6,
            on_token=lambda s, t, i: toks.setdefault(s, []).append(i),
        )
        prompts.append(p)
        sids.append(sid)
    out = router.run()
    assert set(out) == set(sids)
    # both replicas hold live leases carrying their capacity books
    # (checked BEFORE the reference compiles — leases only heartbeat
    # while the router ticks)
    for name in ("r0", "r1"):
        meta = reg.alive_meta()[f"decode:{name}"]
        assert meta["capacity"]["kind"] == "decode"
    ref = _mk_replica(lm, variables)
    rids = [ref.submit(p, steps=6) for p in prompts]
    rout = ref.run()
    for sid, rid in zip(sids, rids):
        np.testing.assert_array_equal(out[sid], rout[rid])
    # exactly-once, in-order token delivery
    for sid in sids:
        assert toks[sid] == list(range(len(out[sid])))
    st = router.stats()
    assert st["placed"] == 6 and st["shed"] == 0
    assert st["replicas_live"] == 2
    pl = router.placements()
    assert len(pl["decisions"]) == 6
    assert all(d["kind"] == "placed" for d in pl["decisions"])
    router.close()


def test_router_prefill_reshard_tp4(clean_slate, lm_setup, sim_mesh):
    """The cross-replica disagg path: a (host) prefill tier streams KV
    to a tp=4 decode replica over the real wire, each leaf resharded
    sender-side into 4 head tiles (never a global gather), landing
    through the prefix cache — bit-identical to collocated prefill."""
    lm, variables = lm_setup
    mesh = sim_mesh(4)
    pf = PrefillWorker(
        lm, variables, page_size=PAGE, prefill_chunk=2 * PAGE
    )
    router = FleetRouter(
        {"d0": _mk_replica(lm, variables, mesh=mesh, tp=4)},
        prefill=pf,
        disagg=DisaggConfig(
            prompt_threshold=2 * PAGE, busy_prompt_threshold=2 * PAGE
        ),
    )
    rng = np.random.RandomState(7)
    prompts = [
        rng.randint(1, VOCAB, size=n).astype(np.int32)
        for n in (37, 29, 50)
    ]
    sids = [router.submit(p, steps=6) for p in prompts]
    out = router.run()
    assert set(out) == set(sids)
    evs = [e["data"] for e in global_flight_recorder().events("kv_handoff")]
    assert len(evs) == 3  # every prompt crossed the wire
    assert all(e["tiles"] == 4 and e["adopted"] for e in evs)
    ref = _mk_replica(lm, variables, mesh=mesh, tp=4)
    rids = [ref.submit(p, steps=6) for p in prompts]
    rout = ref.run()
    for sid, rid in zip(sids, rids):
        np.testing.assert_array_equal(out[sid], rout[rid])
    router.close()


def test_router_kill_one_of_three_midload(clean_slate, lm_setup):
    """The acceptance kill: deregister one of three replicas' leases
    mid-load. The router re-places every stranded request on the leave
    edge within the recovery budget, the re-placed (greedy) streams
    finish bit-identical to an undisturbed reference, and each client
    callback saw every token index exactly once."""
    lm, variables = lm_setup
    reg = WorkerRegistry()
    router = FleetRouter(
        {f"r{i}": _mk_replica(lm, variables) for i in range(3)},
        registry=reg,
        config=RouterConfig(recovery_budget_s=2.0),
    )
    rng = np.random.RandomState(1)
    toks = {}
    prompts, sids = [], []
    for _ in range(9):
        p = rng.randint(1, VOCAB, size=12).astype(np.int32)
        sid = router.submit(
            p, steps=6,
            on_token=lambda s, t, i: toks.setdefault(s, []).append(i),
        )
        prompts.append(p)
        sids.append(sid)
    for _ in range(2):  # let the fleet start decoding
        router.tick()
    victim = max(
        router._replicas.values(), key=lambda r: len(r.sids)
    )
    assert victim.sids  # the kill must strand real work
    reg.deregister(victim.lease_key, victim.lease_token)
    out = router.run()
    assert set(out) == set(sids)
    ref = _mk_replica(lm, variables)
    rids = [ref.submit(p, steps=6) for p in prompts]
    rout = ref.run()
    for sid, rid in zip(sids, rids):
        np.testing.assert_array_equal(out[sid], rout[rid])
    # exactly-once delivery across the re-placement (re-placed
    # requests replay their prefix on the survivor; the watermark
    # suppresses the duplicates)
    for sid in sids:
        assert toks[sid] == list(range(len(out[sid])))
    assert router.replaced > 0
    leaves = [
        e["data"]
        for e in global_flight_recorder().events("replica_leave")
    ]
    assert len(leaves) == 1
    assert leaves[0]["reason"] == "lost"
    assert leaves[0]["moved"] == router.replaced
    assert leaves[0]["wall_s"] < 2.0  # the recovery budget
    assert router.stats()["replicas_live"] == 2
    router.close()


def test_router_sheds_synchronously(clean_slate, lm_setup):
    """Overload sheds at submit through the replicas' own admission
    books: once every live replica's queue is at bound the router
    raises QueueFullError BEFORE any work is queued, books the shed
    and records the decision."""
    lm, variables = lm_setup
    router = FleetRouter({
        "r0": _mk_replica(
            lm, variables,
            scheduler=SchedulerConfig(
                max_queue_depth=2, preempt=False, degrade=False
            ),
        ),
    })
    rng = np.random.RandomState(2)
    accepted, sheds = [], 0
    for _ in range(6):
        p = rng.randint(1, VOCAB, size=8).astype(np.int32)
        try:
            accepted.append(router.submit(p, steps=4))
        except QueueFullError:
            sheds += 1
    assert len(accepted) == 2 and sheds == 4
    assert router.shed == 4
    kinds = [d["kind"] for d in router.placements()["decisions"]]
    assert kinds.count("shed") == 4
    c = global_metrics().snapshot()["counters"]
    assert c["router.shed_total"] == 4
    out = router.run()
    assert set(out) == set(accepted)
    router.close()


def test_autoscaler_up_on_pressure_down_on_drain(clean_slate, lm_setup):
    """Queue pressure above the threshold (held past the dwell) spawns
    a replica BEFORE attainment breaks; a drained fleet retires idle
    replicas back to the floor. Both edges land in the flight stream."""
    lm, variables = lm_setup
    cfg = RouterConfig(
        min_replicas=1, max_replicas=2, scale_up_queue_frac=0.5,
        autoscale_dwell_s=0.0, scale_down_idle_s=0.05,
    )
    sched = SchedulerConfig(
        max_queue_depth=4, preempt=False, degrade=False
    )
    router = FleetRouter(
        {"r0": _mk_replica(lm, variables, scheduler=sched)},
        config=cfg,
    )
    spawned = []

    def spawn(i):
        spawned.append(i)
        return f"auto{i}", _mk_replica(lm, variables, scheduler=sched)

    scaler = FleetAutoscaler(router, spawn, cfg)
    rng = np.random.RandomState(5)
    sids = [
        router.submit(rng.randint(1, VOCAB, size=8).astype(np.int32), 4)
        for _ in range(4)
    ]
    # 2 slots active, 2+ queued of bound 4 -> pressure >= 0.5; dwell
    # is zero so the second tick's autoscale pass fires the spawn.
    for _ in range(3):
        router.tick()
        if scaler.scale_ups:
            break
    assert scaler.scale_ups == 1 and spawned == [1]
    ups = [e["data"] for e in global_flight_recorder().events("scale_up")]
    assert ups and ups[0]["replica"] == "auto1" and ups[0]["fleet"] == 2
    assert router.stats()["replicas_live"] == 2
    out = router.run()
    assert set(out) == set(sids)
    # drained: the spare replica sits idle past the bound and retires
    deadline = time.monotonic() + 5.0
    while not scaler.scale_downs and time.monotonic() < deadline:
        time.sleep(0.02)
        router.tick()
    assert scaler.scale_downs == 1
    downs = [
        e["data"] for e in global_flight_recorder().events("scale_down")
    ]
    assert downs and downs[0]["fleet"] == 1
    assert router.stats()["replicas_live"] == 1
    router.close()


# -- capacity-plane satellites ----------------------------------------------


def test_federated_store_evicts_dead_lease_books():
    """A killed replica's book ages in the fleet view (placement must
    see "stale", not "gone") but past ``capacity_max_age_s`` it evicts
    for good — a replica dead for minutes is not a placement candidate
    and must not scroll a fleet view forever."""
    from adapt_tpu.runtime.capacity import stage_book

    store = FederatedStore()
    reg = WorkerRegistry()
    store.attach_registry(reg)
    token = reg.register(
        "cap-w0", meta={"capacity": stage_book(1, backlog=0)}, ttl_s=60
    )
    assert "lease:cap-w0" in store.capacity_snapshot()["replicas"]
    reg.deregister("cap-w0", token)
    # default retention: the book stays, age growing
    assert "lease:cap-w0" in store.capacity_snapshot()["replicas"]
    store.capacity_max_age_s = 0.01
    time.sleep(0.03)
    assert "lease:cap-w0" not in store.capacity_snapshot()["replicas"]
    # and it stays gone: the retention map itself dropped the entry
    store.capacity_max_age_s = 60.0
    assert "lease:cap-w0" not in store.capacity_snapshot()["replicas"]


def test_fleet_placements_endpoint(clean_slate):
    """``GET /fleet/placements`` serves the router's decision ring
    when a provider is wired, and 404s (never an empty fabrication)
    when the process runs no router."""
    from adapt_tpu.utils.exporter import serve_metrics

    ring = {"v": 1, "router": "router0",
            "decisions": [{"kind": "placed", "replica": "r0"}]}
    srv = serve_metrics(
        port=0, store=FederatedStore(), placements_provider=lambda: ring
    )
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet/placements", timeout=10
        ) as r:
            got = json.loads(r.read().decode())
        assert got == ring
    finally:
        srv.shutdown()
    srv2 = serve_metrics(port=0, store=FederatedStore())
    try:
        port = srv2.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet/placements", timeout=10
            )
        assert ei.value.code == 404
    finally:
        srv2.shutdown()


def _load_fleet_top():
    path = (
        pathlib.Path(__file__).resolve().parent.parent
        / "scripts" / "fleet_top.py"
    )
    spec = importlib.util.spec_from_file_location("fleet_top", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_top_route_column_and_sort():
    ft = _load_fleet_top()
    caps = {"replicas": {
        "lease:decode:r0": {
            "role": "decode", "via": "lease", "age_s": 0.1,
            "book": {
                "health": "ok",
                "headroom": {"slots_free": 1, "slots_total": 2,
                             "queue_frac": 0.1},
                "forecast": {"bias": 1.0, "queue_wait_s": 0.2,
                             "tick_gap_s": 0.0, "samples": 4,
                             "calibration": 0.9, "walls": {"8": 0.01}},
                "sketch": {"entries": [{"h": 1}, {"h": 2}]},
            },
        },
        "lease:decode:r1": {
            "role": "decode", "via": "lease", "age_s": 0.4,
            "book": {
                "health": "degraded",
                "headroom": {"slots_free": 0, "slots_total": 2,
                             "queue_frac": 0.9},
                "forecast": {"bias": 1.0, "queue_wait_s": 0.01,
                             "tick_gap_s": 0.0, "samples": 2,
                             "calibration": 0.8, "walls": {"8": 0.01}},
                "sketch": {"entries": []},
            },
        },
    }}
    placements = {"decisions": [
        {"kind": "placed", "replica": "r0",
         "why": {"affinity_tokens": 96, "forecast_s": 0.02}},
        {"kind": "placed", "replica": "r0",
         "why": {"affinity_tokens": 96, "forecast_s": 0.02}},
        {"kind": "placed", "replica": "r1",
         "why": {"affinity_tokens": 0, "forecast_s": 0.011}},
    ]}
    route, n = ft._route_col("lease:decode:r0", placements)
    assert route == "2x aff:96" and n == 2
    route, _ = ft._route_col("lease:decode:r1", placements)
    assert route == "1x fc:0.011"
    route, _ = ft._route_col("lease:decode:r9", placements)
    assert route == "-"
    rows = ft._rows(caps, {}, placements, sort="key")
    assert [r[0] for r in rows] == ["lease:decode:r0", "lease:decode:r1"]
    assert rows[0][-1] == "2x aff:96"
    # health sort: degraded r1 outranks ok r0
    rows = ft._rows(caps, {}, placements, sort="health")
    assert rows[0][0] == "lease:decode:r1"
    # forecast sort: slowest estimate first (r0's 0.21 > r1's 0.02)
    rows = ft._rows(caps, {}, placements, sort="forecast")
    assert rows[0][0] == "lease:decode:r0"
    # affinity sort: hottest sketch first
    rows = ft._rows(caps, {}, placements, sort="affinity")
    assert rows[0][0] == "lease:decode:r0"
