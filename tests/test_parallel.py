"""SPMD parallelism tests on the 8-device virtual mesh: pipeline schedule
correctness (forward + gradients), ring attention vs full attention, and
TP/DP sharded execution equivalence (ViT encoder + transformer-LM
placement rules). Meshes come from conftest's ``sim_mesh`` factory."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from adapt_tpu.models.vit import EncoderBlock, vit_tiny
from adapt_tpu.parallel.pipeline_spmd import (
    pipeline_microbatch,
    pipeline_unmicrobatch,
    spmd_pipeline,
    stack_stage_params,
)
from adapt_tpu.parallel.ring_attention import full_attention, ring_attention


@pytest.fixture(scope="module")
def pp_mesh(sim_mesh):
    return sim_mesh(4, axis="pp")


@pytest.fixture(scope="module")
def dp_pp_mesh(sim_mesh):
    return sim_mesh((("dp", 2), ("pp", 4)))


@pytest.fixture(scope="module")
def sp_mesh(sim_mesh):
    return sim_mesh(8, axis="sp")


@pytest.fixture(scope="module")
def stacked_blocks(rng=jax.random.PRNGKey(3)):
    """8 identical-structure encoder blocks + their stacked params."""
    block = EncoderBlock(dim=32, heads=4, mlp_dim=64)
    x = jnp.ones((2, 10, 32))
    per_block = []
    for i in range(8):
        rng, sub = jax.random.split(rng)
        per_block.append(block.init(sub, x))
    stacked = stack_stage_params(per_block)
    return block, per_block, stacked


def test_spmd_pipeline_matches_sequential(pp_mesh, stacked_blocks):
    block, per_block, stacked = stacked_blocks
    batch = jax.random.normal(jax.random.PRNGKey(0), (8, 10, 32))
    xs = pipeline_microbatch(batch, num_micro=8)

    def block_fn(params, h):
        return block.apply(params, h)

    y = spmd_pipeline(block_fn, stacked, xs, pp_mesh, axis="pp")
    y = pipeline_unmicrobatch(y)

    h = batch
    for params in per_block:
        h = block.apply(params, h)
    np.testing.assert_allclose(np.asarray(y), np.asarray(h), rtol=2e-5, atol=2e-5)


def test_spmd_pipeline_with_dp(dp_pp_mesh, stacked_blocks):
    block, per_block, stacked = stacked_blocks
    batch = jax.random.normal(jax.random.PRNGKey(1), (8, 10, 32))
    xs = pipeline_microbatch(batch, num_micro=4)  # mb=2, sharded over dp=2

    def block_fn(params, h):
        return block.apply(params, h)

    y = spmd_pipeline(
        block_fn, stacked, xs, dp_pp_mesh, axis="pp", batch_axis="dp"
    )
    y = pipeline_unmicrobatch(y)
    h = batch
    for params in per_block:
        h = block.apply(params, h)
    np.testing.assert_allclose(np.asarray(y), np.asarray(h), rtol=2e-5, atol=2e-5)


def test_spmd_pipeline_differentiable(pp_mesh, stacked_blocks):
    """Pipelined training: grads through scan+ppermute must equal the
    sequential model's grads."""
    block, per_block, stacked = stacked_blocks
    batch = jax.random.normal(jax.random.PRNGKey(2), (4, 10, 32))
    xs = pipeline_microbatch(batch, num_micro=4)

    def block_fn(params, h):
        return block.apply(params, h)

    def pipelined_loss(stacked_params):
        y = spmd_pipeline(block_fn, stacked_params, xs, pp_mesh, axis="pp")
        return jnp.mean(y**2)

    def sequential_loss(stacked_params):
        h = batch
        for i in range(8):
            params_i = jax.tree.map(lambda p: p[i], stacked_params)
            h = block.apply(params_i, h)
        return jnp.mean(h**2)

    g_pipe = jax.grad(pipelined_loss)(stacked)
    g_seq = jax.grad(sequential_loss)(stacked)
    flat_p = jax.tree.leaves(g_pipe)
    flat_s = jax.tree.leaves(g_seq)
    for a, b in zip(flat_p, flat_s):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_pipeline_bad_divisibility(pp_mesh, stacked_blocks):
    block, _, stacked = stacked_blocks
    trimmed = jax.tree.map(lambda p: p[:6], stacked)  # 6 % 4 != 0
    xs = jnp.zeros((4, 2, 10, 32))
    with pytest.raises(ValueError, match="not divisible"):
        spmd_pipeline(lambda p, h: block.apply(p, h), trimmed, xs, pp_mesh)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(sp_mesh, causal):
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    shape = (2, 4, 64, 16)  # [B, H, S, D], S=64 over 8 ranks
    q = jax.random.normal(kq, shape)
    k = jax.random.normal(kk, shape)
    v = jax.random.normal(kv, shape)
    y_ring = ring_attention(q, k, v, sp_mesh, axis="sp", causal=causal)
    y_full = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(y_ring), np.asarray(y_full), rtol=2e-5, atol=2e-5
    )


def test_ring_attention_bad_seq(sp_mesh):
    q = jnp.zeros((1, 2, 30, 8))
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, q, q, sp_mesh)


def test_tp_dp_sharded_vit_matches_replicated(sim_mesh):
    """jit the full ViT-tiny with batch over dp and megatron TP rules over
    tp; GSPMD-inserted collectives must not change the math."""
    from adapt_tpu.parallel.sharding import shard_batch, tree_shardings

    mesh = sim_mesh((("dp", 2), ("tp", 4)))
    g = vit_tiny()
    x = jnp.ones((4, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    y_ref = np.asarray(jax.jit(g.apply)(variables, x))

    shardings = tree_shardings(variables, mesh)
    sharded_vars = jax.device_put(variables, shardings)
    x_sharded = shard_batch(x, mesh, "dp")
    y = jax.jit(g.apply)(sharded_vars, x_sharded)
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(sp_mesh, causal):
    from adapt_tpu.parallel.ulysses import ulysses_attention

    b, h, s, d = 2, 8, 64, 16  # h == sp size, s divisible by 8
    key = jax.random.PRNGKey(11)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, s, d))
    k = jax.random.normal(kk, (b, h, s, d))
    v = jax.random.normal(kv, (b, h, s, d))
    out = ulysses_attention(q, k, v, sp_mesh, axis="sp", causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ulysses_rejects_indivisible_heads(sp_mesh):
    from adapt_tpu.parallel.ulysses import ulysses_attention

    q = jnp.ones((1, 6, 64, 8))  # 6 heads not divisible by 8 ranks
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, q, q, sp_mesh, axis="sp")


def test_ulysses_with_flash_block(sp_mesh):
    import functools

    from adapt_tpu.ops import flash_attention
    from adapt_tpu.parallel.ulysses import ulysses_attention

    b, h, s, d = 1, 8, 128, 16
    q = jax.random.normal(jax.random.PRNGKey(12), (b, h, s, d))
    out = ulysses_attention(
        q,
        q,
        q,
        sp_mesh,
        axis="sp",
        causal=True,
        # Pin the Pallas path: the measured dispatch would route these
        # small per-device shards to XLA (ops.attention.FLASH_MIN_SEQ).
        attn_fn=functools.partial(flash_attention, prefer="pallas"),
    )
    ref = full_attention(q, q, q, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ulysses_default_dispatch_uses_shared_predicate(sp_mesh, monkeypatch):
    """attn_fn=None consults the SAME measured predicate as the kernel's
    own dispatch and ring's "auto" (VERDICT r3 weak #3: asymmetric
    dispatch is drift): with the budget patched to 0 the default ulysses
    block compute runs the streaming kernel; with the real budget these
    small shards run XLA. Paths observed via the same module-global
    seams the kernel dispatch test uses."""
    import adapt_tpu.ops.attention as A
    from adapt_tpu.parallel.ulysses import ulysses_attention

    b, h, s, d = 1, 8, 128, 16
    q = jax.random.normal(jax.random.PRNGKey(13), (b, h, s, d))
    ref = full_attention(q, q, q, causal=True)

    calls = []
    real_vjp, real_oracle = A._flash_vjp, A.attention_reference
    monkeypatch.setattr(
        A,
        "_flash_vjp",
        lambda *a, **kw: calls.append("pallas") or real_vjp(*a, **kw),
    )
    monkeypatch.setattr(
        A,
        "attention_reference",
        lambda *a, **kw: calls.append("xla") or real_oracle(*a, **kw),
    )

    out = ulysses_attention(q, q, q, sp_mesh, axis="sp", causal=True)
    assert set(calls) == {"xla"}  # sub-budget shard -> fused XLA path
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )

    calls.clear()
    monkeypatch.setattr(A, "FLASH_SCORE_BYTES_BUDGET", 0)
    out = ulysses_attention(q, q, q, sp_mesh, axis="sp", causal=True)
    assert set(calls) == {"pallas"}  # super-budget shard -> kernel
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=5e-3, atol=5e-3
    )


def test_vit_tp_rules_cover_attention_params(rng, sim_mesh):
    """Every encoder-block matmul weight must get a real TP split —
    regression for the attention-module rename silently falling through to
    replicated (P()) because the rules still matched flax's old
    query/key/value param names."""
    from jax.sharding import PartitionSpec as P

    from adapt_tpu.models.vit import vit_tiny
    from adapt_tpu.parallel.sharding import tree_shardings

    g = vit_tiny()
    variables = g.init(rng, jnp.ones((1, 32, 32, 3)))
    mesh = sim_mesh((("dp", 1), ("tp", 2)))
    shardings = tree_shardings(variables, mesh)

    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    specs = {
        "/".join(str(getattr(k, "key", k)) for k in path): s.spec
        for path, s in flat
    }
    block = {p: s for p, s in specs.items() if "encoder_block_0" in p}
    assert block, "no encoder block params found"
    # Attention qkv column-split on the heads axis, out row-split.
    qkv_kernel = next(s for p, s in block.items() if "attn/qkv/kernel" in p)
    assert "tp" in tuple(qkv_kernel), qkv_kernel
    out_kernel = next(s for p, s in block.items() if "attn/out/kernel" in p)
    assert out_kernel == P("tp", None), out_kernel
    # MLP in/out splits still live.
    mlp_in = next(s for p, s in block.items() if "Dense_0/kernel" in p)
    assert mlp_in == P(None, "tp"), mlp_in
    mlp_out = next(s for p, s in block.items() if "Dense_1/kernel" in p)
    assert mlp_out == P("tp", None), mlp_out


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_blocks(sp_mesh, causal):
    """block_impl='flash' (streaming-kernel blocks + logsumexp merge)
    matches the single-device oracle, causal and not."""
    from adapt_tpu.parallel.ring_attention import ring_attention

    b, h, s, d = 1, 2, 8 * 16, 16
    q = jax.random.normal(jax.random.PRNGKey(20), (b, h, s, d))
    k = jax.random.normal(jax.random.PRNGKey(21), (b, h, s, d))
    v = jax.random.normal(jax.random.PRNGKey(22), (b, h, s, d))
    out = ring_attention(
        q, k, v, sp_mesh, axis="sp", causal=causal, block_impl="flash"
    )
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_ring_attention_auto_block_dispatch(sp_mesh, monkeypatch):
    """'auto' keeps the differentiable jnp path for small shards and
    switches to the streaming kernel when one score block would bust the
    measured budget."""
    import importlib

    # The package __init__ rebinds the name `ring_attention` to the
    # FUNCTION, so `import ... as R` would grab that instead of the module.
    R = importlib.import_module("adapt_tpu.parallel.ring_attention")

    calls = []
    real = R._ring_attention_flash
    monkeypatch.setattr(
        R,
        "_ring_attention_flash",
        lambda *a, **kw: calls.append(True) or real(*a, **kw),
    )
    b, h, s, d = 1, 2, 8 * 16, 16
    q = jax.random.normal(jax.random.PRNGKey(23), (b, h, s, d))
    # small -> jnp (and the default block_impl is plain "jnp" outright:
    # flash is forward-only, so training code must never land on it
    # without asking)
    R.ring_attention(q, q, q, sp_mesh, axis="sp", block_impl="auto")
    assert not calls
    import adapt_tpu.ops.attention as A

    monkeypatch.setattr(A, "FLASH_SCORE_BYTES_BUDGET", 0)
    monkeypatch.setattr(A, "FLASH_MIN_SEQ", 1)
    R.ring_attention(q, q, q, sp_mesh, axis="sp", block_impl="auto")
    assert calls


# -- striped (balanced causal) ring attention -------------------------------


def test_stripe_unstripe_roundtrip():
    from adapt_tpu.parallel.ring_attention import (
        stripe_sequence,
        unstripe_sequence,
    )

    x = jax.random.normal(jax.random.PRNGKey(30), (2, 3, 24, 5))
    s = stripe_sequence(x, 8)
    np.testing.assert_array_equal(np.asarray(unstripe_sequence(s, 8)), x)
    # Layout contract: striped[r*s_local + i] == x[i*P + r].
    np.testing.assert_array_equal(
        np.asarray(s[:, :, 1 * 3 + 2]), np.asarray(x[:, :, 2 * 8 + 1])
    )
    with pytest.raises(ValueError, match="not divisible"):
        stripe_sequence(x, 7)


@pytest.mark.parametrize("block_impl", ["jnp", "flash"])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_striped_matches_full(sp_mesh, causal, block_impl):
    """layout='striped' (the balanced causal layout: stripe inputs, ring,
    unstripe output) must equal the single-device oracle exactly like the
    contiguous layout does — for both block impls. Under causal+flash
    this path uses the kernel's traced causal_shift with NO lax.cond."""
    from adapt_tpu.parallel.ring_attention import (
        ring_attention,
        stripe_sequence,
        unstripe_sequence,
    )

    P_ = 8
    b, h, s, d = 1, 2, 8 * 16, 16
    q = jax.random.normal(jax.random.PRNGKey(31), (b, h, s, d))
    k = jax.random.normal(jax.random.PRNGKey(32), (b, h, s, d))
    v = jax.random.normal(jax.random.PRNGKey(33), (b, h, s, d))
    out = ring_attention(
        stripe_sequence(q, P_),
        stripe_sequence(k, P_),
        stripe_sequence(v, P_),
        sp_mesh,
        axis="sp",
        causal=causal,
        block_impl=block_impl,
        layout="striped",
    )
    out = unstripe_sequence(out, P_)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_ring_attention_bad_layout(sp_mesh):
    q = jnp.zeros((1, 2, 16, 8))
    with pytest.raises(ValueError, match="layout"):
        ring_attention(q, q, q, sp_mesh, layout="zigzag")


# -- overlap schedule -------------------------------------------------------


@pytest.mark.parametrize(
    "ranks,hop_buffers", [(2, 2), (3, 2), (4, 2), (4, 3)]
)
def test_spmd_overlap_matches_serial_bitexact(
    sim_mesh, stacked_blocks, ranks, hop_buffers
):
    """The overlap schedule must be a pure PERF knob: for 2-4 stages
    (and a deeper hop buffer) its outputs are BIT-IDENTICAL to the
    serial schedule — every microbatch runs the same blocks in the same
    order, only the tick each hop occupies moves."""
    block, per_block, stacked = stacked_blocks
    if len(per_block) % ranks:
        stacked = jax.tree.map(lambda x: x[: 2 * ranks], stacked)
    mesh = sim_mesh(ranks, axis="pp")
    batch = jax.random.normal(jax.random.PRNGKey(7), (8, 10, 32))
    xs = pipeline_microbatch(batch, num_micro=8)

    def block_fn(params, h):
        return block.apply(params, h)

    y_serial = spmd_pipeline(
        block_fn, stacked, xs, mesh, axis="pp", schedule="serial"
    )
    y_overlap = spmd_pipeline(
        block_fn, stacked, xs, mesh, axis="pp", schedule="overlap",
        hop_buffers=hop_buffers,
    )
    np.testing.assert_array_equal(
        np.asarray(y_serial), np.asarray(y_overlap)
    )


def test_spmd_overlap_with_dp_bitexact(dp_pp_mesh, stacked_blocks):
    """Overlap == serial also when the microbatch dim is additionally
    dp-sharded in the same program."""
    block, _, stacked = stacked_blocks
    batch = jax.random.normal(jax.random.PRNGKey(8), (8, 10, 32))
    xs = pipeline_microbatch(batch, num_micro=4)

    def block_fn(params, h):
        return block.apply(params, h)

    kw = dict(axis="pp", batch_axis="dp")
    y_serial = spmd_pipeline(
        block_fn, stacked, xs, dp_pp_mesh, schedule="serial", **kw
    )
    y_overlap = spmd_pipeline(
        block_fn, stacked, xs, dp_pp_mesh, schedule="overlap", **kw
    )
    np.testing.assert_array_equal(
        np.asarray(y_serial), np.asarray(y_overlap)
    )


def test_spmd_pipeline_from_config_knobs(pp_mesh, stacked_blocks):
    """config.PipelineConfig drives the schedule end to end (split ->
    schedule -> unsplit), and both knob settings agree with the
    sequential oracle."""
    from adapt_tpu.config import PipelineConfig
    from adapt_tpu.parallel.pipeline_spmd import spmd_pipeline_from_config

    block, per_block, stacked = stacked_blocks
    batch = jax.random.normal(jax.random.PRNGKey(9), (8, 10, 32))

    def block_fn(params, h):
        return block.apply(params, h)

    h = batch
    for params in per_block:
        h = block.apply(params, h)
    for cfg in (
        PipelineConfig(schedule="serial", microbatches=8),
        PipelineConfig(schedule="overlap", microbatches=8, hop_buffers=3),
    ):
        y = spmd_pipeline_from_config(
            block_fn, stacked, batch, pp_mesh, cfg, axis="pp"
        )
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(h), rtol=2e-5, atol=2e-5
        )


def test_spmd_schedule_knobs_validated(pp_mesh, stacked_blocks):
    from adapt_tpu.config import PipelineConfig

    block, _, stacked = stacked_blocks
    xs = pipeline_microbatch(jnp.ones((8, 10, 32)), 8)
    with pytest.raises(ValueError, match="schedule"):
        spmd_pipeline(
            lambda p, h: block.apply(p, h), stacked, xs, pp_mesh,
            schedule="eager",
        )
    with pytest.raises(ValueError, match="hop_buffers"):
        spmd_pipeline(
            lambda p, h: block.apply(p, h), stacked, xs, pp_mesh,
            schedule="overlap", hop_buffers=1,
        )
    with pytest.raises(ValueError, match="schedule"):
        PipelineConfig(schedule="eager")
    with pytest.raises(ValueError, match="hop_buffers"):
        PipelineConfig(hop_buffers=0)


# -- transformer-LM TP placement rules --------------------------------------


def _flat_specs(tree):
    """{path: PartitionSpec} for a tree_shardings result."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {
        "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        ): s.spec
        for path, s in flat
    }


def _lm_gqa_moe(rng):
    from adapt_tpu.models.transformer_lm import transformer_lm

    lm = transformer_lm(37, 32, 2, 4, 64, max_len=48, kv_heads=2,
                        moe_experts=4)
    variables = lm.graph.init(rng, jnp.zeros((1, 4), jnp.int32))
    return lm, variables


def test_lm_tp_rules_cover_gqa_moe_params(rng, sim_mesh):
    """Every param path in a GQA+MoE TransformerLM matches AT MOST one
    placement rule, the matmul weights that must shard match EXACTLY
    one, and the column/row splits land on the intended axes (heads /
    kv-heads / hidden columns; contracted dims rows). Norms, embeds,
    the MoE router gate and the post-psum biases replicate."""
    import re

    from adapt_tpu.parallel.sharding import (
        _LM_TP_PATTERNS,
        lm_tp_rules,
        tree_shardings,
    )

    _, variables = _lm_gqa_moe(rng)
    mesh = sim_mesh(2)
    specs = _flat_specs(
        tree_shardings(variables, mesh, rules=lm_tp_rules)
    )
    flat = jax.tree_util.tree_flatten_with_path(variables)[0]
    ndims = {
        "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        ): leaf.ndim
        for path, leaf in flat
    }
    for path, nd in ndims.items():
        matches = [
            pat for pat, spec in _LM_TP_PATTERNS
            if re.fullmatch(pat, path) and len(spec) == nd
        ]
        assert len(matches) <= 1, (path, matches)

    # GQA attention: q/kv column-split on their HEAD axes, out row-split.
    assert specs["decoder_block_0/params/attn/q/kernel"] == P(
        None, "tp", None
    )
    assert specs["decoder_block_0/params/attn/q/bias"] == P("tp", None)
    assert specs["decoder_block_0/params/attn/kv/kernel"] == P(
        None, None, "tp", None
    )
    assert specs["decoder_block_0/params/attn/kv/bias"] == P(
        None, "tp", None
    )
    assert specs["decoder_block_0/params/attn/out/kernel"] == P("tp", None)
    # MoE experts: HIDDEN axis splits, expert axis left for 'ep'.
    assert specs["decoder_block_0/params/moe/w1"] == P(None, None, "tp")
    assert specs["decoder_block_0/params/moe/b1"] == P(None, "tp")
    assert specs["decoder_block_0/params/moe/w2"] == P(None, "tp", None)
    # Head: row split on the contracted model dim (logits replicate
    # after one psum — sampling is sharding-blind).
    assert specs["head/params/logits/kernel"] == P("tp", None)
    # Everything position/norm/router-side replicates.
    for path in (
        "decoder_block_0/params/ln1/scale",
        "decoder_block_0/params/ln2/bias",
        "decoder_block_0/params/attn/out/bias",
        "decoder_block_0/params/moe/gate",
        "decoder_block_0/params/moe/b2",
        "embed/params/tok/embedding",
        "embed/params/pos_embed",
        "head/params/logits/bias",
    ):
        assert specs[path] == P(), path
    # Dense-MLP variant of the same rules (no MoE): in column / out row.
    from adapt_tpu.models.transformer_lm import transformer_lm

    dense = transformer_lm(37, 32, 1, 4, 64, max_len=48)
    dvars = dense.graph.init(rng, jnp.zeros((1, 4), jnp.int32))
    dspecs = _flat_specs(
        tree_shardings(dvars, mesh, rules=lm_tp_rules)
    )
    assert dspecs["decoder_block_0/params/mlp_in/kernel"] == P(None, "tp")
    assert dspecs["decoder_block_0/params/mlp_in/bias"] == P("tp")
    assert dspecs["decoder_block_0/params/mlp_out/kernel"] == P("tp", None)
    assert dspecs["decoder_block_0/params/mlp_out/bias"] == P()
    # Fused-QKV MHA variant: the heads axis of the (d, 3, h, hd) kernel.
    assert dspecs["decoder_block_0/params/attn/qkv/kernel"] == P(
        None, None, "tp", None
    )
    assert dspecs["decoder_block_0/params/attn/qkv/bias"] == P(
        None, "tp", None
    )


def test_lm_tp_expert_params_compose_with_ep(rng, sim_mesh):
    """The MoE expert weights' TP spec (hidden axis) composes with
    parallel/expert.py's EP spec (leading expert axis) via merge_specs,
    and the merged placement actually lands: on an (ep=2, tp=2) mesh
    each device holds E/2 experts x hidden/2 columns."""
    from adapt_tpu.parallel.expert import expert_shardings
    from adapt_tpu.parallel.sharding import lm_tp_rules, merge_specs

    _, variables = _lm_gqa_moe(rng)
    mesh = sim_mesh((("ep", 2), ("tp", 2)))
    moe = variables["decoder_block_0"]["params"]["moe"]
    ep_specs = _flat_specs(
        expert_shardings(moe, mesh, num_experts=4)
    )
    merged_w1 = merge_specs(
        ep_specs["w1"],
        lm_tp_rules("decoder_block_0/params/moe/w1", moe["w1"].ndim),
    )
    assert merged_w1 == P("ep", None, "tp")
    merged_w2 = merge_specs(
        ep_specs["w2"],
        lm_tp_rules("decoder_block_0/params/moe/w2", moe["w2"].ndim),
    )
    assert merged_w2 == P("ep", "tp", None)
    placed = jax.device_put(
        moe["w1"], NamedSharding(mesh, merged_w1)
    )  # (4, 32, 64) experts x d x hidden
    assert placed.sharding.shard_shape(placed.shape) == (2, 32, 32)
    # The router gate stays replicated under BOTH placements.
    assert ep_specs["gate"] == P()
    assert lm_tp_rules("decoder_block_0/params/moe/gate", 2) == P()
    with pytest.raises(ValueError, match="conflict"):
        merge_specs(P("ep", None), P("tp", None))


def test_lm_tp_sharded_serving_matches_replicated(rng, sim_mesh):
    """End to end: a GQA LM placed by lm_tp_rules on a tp=4 mesh emits
    the same greedy tokens as the unsharded model (GSPMD collectives
    change reduction order, never the decoded stream), and the full-
    sequence logits agree to fp tolerance."""
    from jax.sharding import NamedSharding as NS

    from adapt_tpu.models.transformer_lm import (
        generate,
        logits_full,
        transformer_lm,
    )
    from adapt_tpu.parallel.sharding import lm_tp_rules, tree_shardings

    lm = transformer_lm(37, 32, 2, 8, 64, max_len=48, kv_heads=4)
    variables = lm.graph.init(rng, jnp.zeros((1, 4), jnp.int32))
    mesh = sim_mesh(4)
    sharded = jax.device_put(
        variables, tree_shardings(variables, mesh, rules=lm_tp_rules)
    )
    ids = jnp.asarray([[1, 5, 9, 2]], jnp.int32)
    np.testing.assert_allclose(
        np.asarray(logits_full(lm, sharded, jax.device_put(
            ids, NS(mesh, P())
        ))),
        np.asarray(logits_full(lm, variables, ids)),
        rtol=2e-5, atol=2e-5,
    )
    np.testing.assert_array_equal(
        np.asarray(generate(lm, sharded, ids, 8)),
        np.asarray(generate(lm, variables, ids, 8)),
    )
