"""Pallas-kernel tests (interpreter mode on the virtual CPU mesh).

The kernels are the TPU hot-ops layer: blockwise int8 quantization (the
reference's per-hop lossy codec re-expressed on-device, SURVEY.md §2.3)
and fused flash attention (the ViT / ring-attention block compute).
Oracles are the pure-jnp ``*_reference`` implementations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.ops import (
    attention_reference,
    dequantize,
    dequantize_reference,
    flash_attention,
    quantize,
    quantize_reference,
)


# -- quantize ---------------------------------------------------------------


@pytest.mark.parametrize(
    "shape", [(8, 8), (2, 224, 3), (4, 64, 128), (1, 8191)]
)
def test_quantize_matches_reference(rng, shape):
    x = jax.random.normal(rng, shape) * 5.0
    qt = quantize(x)
    ref = quantize_reference(x)
    np.testing.assert_array_equal(np.asarray(qt.values), np.asarray(ref.values))
    np.testing.assert_allclose(
        np.asarray(qt.scales), np.asarray(ref.scales), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(dequantize(qt)),
        np.asarray(dequantize_reference(ref)),
        rtol=1e-6,
        atol=1e-6,
    )


def test_quantize_roundtrip_error_bound(rng):
    x = jax.random.normal(rng, (32, 512)) * 3.0
    y = dequantize(quantize(x))
    assert y.shape == x.shape and y.dtype == x.dtype
    # Per-block absmax scaling bounds error by scale/2 = absmax/254.
    err = np.abs(np.asarray(y) - np.asarray(x)).max()
    assert err <= float(jnp.abs(x).max()) / 254.0 + 1e-6


def test_quantize_preserves_dtype_bf16(rng):
    x = jax.random.normal(rng, (16, 256)).astype(jnp.bfloat16)
    y = dequantize(quantize(x))
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(x, np.float32),
        atol=float(jnp.abs(x.astype(jnp.float32)).max()) / 100.0,
    )


def test_quantized_tensor_is_pytree(rng):
    x = jax.random.normal(rng, (8, 128))
    qt = quantize(x)
    moved = jax.tree.map(lambda a: a, qt)
    np.testing.assert_array_equal(
        np.asarray(moved.values), np.asarray(qt.values)
    )
    assert moved.shape == qt.shape


def test_quantize_constant_and_zero_blocks():
    x = jnp.zeros((64, 128))
    y = dequantize(quantize(x))
    np.testing.assert_array_equal(np.asarray(y), 0.0)
    x2 = jnp.full((64, 128), 7.5)
    y2 = dequantize(quantize(x2))
    np.testing.assert_allclose(np.asarray(y2), 7.5, rtol=1e-2)


# -- flash attention --------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(rng, causal):
    b, h, s, d = 2, 2, 256, 64
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, h, s, d))
    k = jax.random.normal(kk, (b, h, s, d))
    v = jax.random.normal(kv, (b, h, s, d))
    out = flash_attention(
        q, k, v, causal=causal, block_q=128, block_k=128, prefer="pallas"
    )
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_flash_attention_small_blocks(rng):
    b, h, s, d = 1, 2, 128, 32
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, h, s, d))
    k = jax.random.normal(kk, (b, h, s, d))
    v = jax.random.normal(kv, (b, h, s, d))
    out = flash_attention(
        q, k, v, causal=True, block_q=32, block_k=64, prefer="pallas"
    )
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_flash_attention_indivisible_falls_back(rng):
    # s=200 > default block 128 and 200 % 128 != 0 -> reference fallback.
    b, h, s, d = 1, 1, 200, 16
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, h, s, d))
    k = jax.random.normal(kk, (b, h, s, d))
    v = jax.random.normal(kv, (b, h, s, d))
    out = flash_attention(q, k, v, causal=False, prefer="pallas")
    ref = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_flash_attention_bf16(rng):
    b, h, s, d = 1, 2, 128, 64
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, h, s, d)).astype(jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, s, d)).astype(jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, s, d)).astype(jnp.bfloat16)
    out = flash_attention(q, k, v, prefer="pallas")
    assert out.dtype == jnp.bfloat16
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


@pytest.mark.parametrize(
    "b,h,s,d,causal",
    [
        (2, 4, 197, 64, False),  # ViT-B/16's 14^2+CLS — the ragged case
        (1, 2, 197, 32, True),
        (1, 1, 130, 8, True),
    ],
)
def test_flash_attention_ragged_sequences(b, h, s, d, causal):
    """Non-block-divisible sequence lengths run the Pallas path via
    internal zero-padding + key masking (regression: they silently fell
    back to the jnp oracle, so ViT-B/16 at 224px never used the kernel)."""
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, h, s, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, h, s, d))
    out = flash_attention(q, k, v, causal=causal, prefer="pallas")
    ref = attention_reference(q, k, v, causal=causal)
    assert out.shape == ref.shape
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=5e-3, atol=5e-3
    )

def test_flash_attention_dispatch_heuristic(rng, monkeypatch):
    """Default dispatch is measured, not dogmatic: small score tensors
    route to the XLA path (which beat the kernel 1.9x end-to-end at ViT
    shapes on the real chip, benchmarks/results/r03/), score tensors past
    the HBM budget stream through the Pallas kernel (XLA OOMs outright at
    32k, attn_longseq.json). Paths are stubbed — this tests routing, not
    the kernels (covered above)."""
    import adapt_tpu.ops.attention as A

    calls = []
    monkeypatch.setattr(
        A, "_flash_vjp", lambda q, *a: calls.append("pallas") or q
    )
    monkeypatch.setattr(
        A, "attention_reference", lambda q, *a, **kw: calls.append("xla") or q
    )
    short = jax.random.normal(rng, (1, 2, 128, 32))
    A.flash_attention(short, short, short)
    assert calls == ["xla"]

    calls.clear()
    # (1, 1, 32768, 32): scores = 32768^2 * 4B = 4 GiB > the 2 GiB budget.
    long = jax.ShapeDtypeStruct((1, 1, 32768, 32), jnp.bfloat16)
    jax.eval_shape(lambda t: A.flash_attention(t, t, t), long)
    assert calls == ["pallas"]

    calls.clear()
    # prefer= overrides the heuristic both ways.
    A.flash_attention(short, short, short, prefer="pallas")
    jax.eval_shape(
        lambda t: A.flash_attention(t, t, t, prefer="xla"), long
    )
    assert calls == ["pallas", "xla"]

@pytest.mark.parametrize(
    "b,h,s,d,causal",
    [
        (1, 2, 256, 32, False),
        (1, 2, 256, 32, True),
        (1, 1, 197, 16, False),  # ragged: padded rows/cols must zero out
    ],
)
def test_flash_attention_streaming_backward(b, h, s, d, causal, monkeypatch):
    """Gradients through the streaming Pallas backward match the oracle.
    The budget is patched to 0 so these small shapes exercise the
    streaming path (by default they'd take the materialized-recompute
    branch, which is faster where scores fit)."""
    import adapt_tpu.ops.attention as A

    monkeypatch.setattr(A, "FLASH_SCORE_BYTES_BUDGET", 0)
    q = jax.random.normal(jax.random.PRNGKey(3), (b, h, s, d))
    k = jax.random.normal(jax.random.PRNGKey(4), (b, h, s, d))
    v = jax.random.normal(jax.random.PRNGKey(5), (b, h, s, d))

    def loss_flash(q, k, v):
        o = A.flash_attention(q, k, v, causal=causal, prefer="pallas")
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(attention_reference(q, k, v, causal=causal)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf),
            np.asarray(gr),
            rtol=2e-4,
            atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_attention_backward_dispatch(monkeypatch):
    """Sub-budget gradients take the materialized-recompute branch; the
    streaming kernels are reserved for super-budget shapes."""
    import adapt_tpu.ops.attention as A

    called = []
    real = A._flash_bwd_impl
    monkeypatch.setattr(
        A,
        "_flash_bwd_impl",
        lambda *a, **kw: called.append(True) or real(*a, **kw),
    )
    q = jax.random.normal(jax.random.PRNGKey(6), (1, 1, 128, 16))
    jax.grad(
        lambda q: jnp.sum(A.flash_attention(q, q, q, prefer="pallas"))
    )(q)
    assert not called  # small shape -> jnp recompute branch
    monkeypatch.setattr(A, "FLASH_SCORE_BYTES_BUDGET", 0)
    jax.grad(
        lambda q: jnp.sum(A.flash_attention(q, q, q, prefer="pallas"))
    )(q)
    assert called


@pytest.mark.parametrize(
    "b,h,s,d,causal",
    [
        (3, 2, 256, 32, True),  # block-divisible, the LM prefill shape class
        (3, 2, 256, 32, False),
        (2, 2, 197, 16, True),  # ragged tail AND ragged head together
    ],
)
def test_flash_attention_valid_from_matches_oracle(b, h, s, d, causal):
    """Per-row left-padding (valid_from) inside the kernel must match the
    masked oracle on every VALID query row. Fully-padded rows (position
    < vf) are unspecified — zeros if every k-block was skipped, a
    uniform average if the row shares a k-block with live keys — and no
    caller reads them (the LM masks those positions out of every
    downstream attention window)."""
    q = jax.random.normal(jax.random.PRNGKey(7), (b, h, s, d))
    k = jax.random.normal(jax.random.PRNGKey(8), (b, h, s, d))
    v = jax.random.normal(jax.random.PRNGKey(9), (b, h, s, d))
    vf = jnp.asarray([0, s // 3, min(s - 1, 200)][:b], jnp.int32)
    out = flash_attention(
        q, k, v, causal=causal, valid_from=vf, prefer="pallas"
    )
    ref = attention_reference(q, k, v, causal=causal, valid_from=vf)
    rows_valid = jnp.arange(s)[None, :] >= vf[:, None]  # (b, s)
    mask = rows_valid[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(jnp.where(mask, out, 0.0)),
        np.asarray(jnp.where(mask, ref, 0.0)),
        rtol=5e-3,
        atol=5e-3,
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_valid_from_streaming_backward(causal, monkeypatch):
    """Gradients through the vf-masked streaming backward match the
    masked oracle when the loss reads only valid rows (the only contract
    any ragged caller relies on). Budget patched to 0 so the small shape
    exercises the streaming kernels."""
    import adapt_tpu.ops.attention as A

    monkeypatch.setattr(A, "FLASH_SCORE_BYTES_BUDGET", 0)
    b, h, s, d = 2, 2, 128, 16
    q = jax.random.normal(jax.random.PRNGKey(10), (b, h, s, d))
    k = jax.random.normal(jax.random.PRNGKey(11), (b, h, s, d))
    v = jax.random.normal(jax.random.PRNGKey(12), (b, h, s, d))
    vf = jnp.asarray([5, 40], jnp.int32)
    row_mask = (jnp.arange(s)[None, :] >= vf[:, None])[:, None, :, None]

    def loss_flash(q, k, v):
        o = A.flash_attention(
            q, k, v, causal=causal, valid_from=vf, prefer="pallas"
        )
        return jnp.sum(jnp.where(row_mask, jnp.sin(o), 0.0))

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=causal, valid_from=vf)
        return jnp.sum(jnp.where(row_mask, jnp.sin(o), 0.0))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf),
            np.asarray(gr),
            rtol=2e-4,
            atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_ragged_prefill_routes_through_measured_dispatch(monkeypatch):
    """prefill(valid_from=...) no longer hardcodes the oracle: past the
    budget it runs the vf-masked kernel (here: budget patched to 0 and
    the kernel entry instrumented)."""
    import adapt_tpu.ops.attention as A
    from adapt_tpu.models.transformer_lm import lm_tiny

    lm = lm_tiny(vocab=31, max_len=32)
    prompt = jax.random.randint(jax.random.PRNGKey(13), (2, 8), 0, 31)
    variables = lm.graph.init(jax.random.PRNGKey(14), prompt)

    calls = []
    real = A._flash_impl
    monkeypatch.setattr(
        A,
        "_flash_impl",
        lambda *a, **kw: calls.append(kw.get("valid_from") is not None)
        or real(*a, **kw),
    )
    monkeypatch.setattr(A, "FLASH_SCORE_BYTES_BUDGET", 0)
    from adapt_tpu.models.transformer_lm import generate

    generate(
        lm, variables, prompt, 2, prompt_lengths=jnp.asarray([3, 8])
    )
    # One vf-masked kernel call per decoder block, no dense/oracle calls.
    assert calls == [True] * lm.depth, calls


def test_flash_with_lse_causal_shift_matches_reference():
    """causal_shift offsets the kernel's causal diagonal (row i attends
    cols <= i - shift); out and lse must match the masked oracle on every
    row that has at least one live key (rows < shift have unspecified
    out and lse ~ -inf — the merge-neutral element)."""
    from adapt_tpu.ops.attention import (
        _reference_with_lse,
        flash_attention_with_lse,
    )

    b, h, s, d = 1, 2, 64, 16
    q = jax.random.normal(jax.random.PRNGKey(50), (b, h, s, d))
    k = jax.random.normal(jax.random.PRNGKey(51), (b, h, s, d))
    v = jax.random.normal(jax.random.PRNGKey(52), (b, h, s, d))
    for shift in (0, 1):
        out, lse = flash_attention_with_lse(
            q, k, v, causal=True,
            causal_shift=jnp.asarray(shift, jnp.int32),
        )
        ref_out, ref_lse = _reference_with_lse(
            q, k, v, True, causal_shift=jnp.asarray(shift, jnp.int32)
        )
        np.testing.assert_allclose(
            np.asarray(out)[:, :, shift:],
            np.asarray(ref_out)[:, :, shift:],
            rtol=2e-4, atol=2e-4, err_msg=f"shift={shift}",
        )
        np.testing.assert_allclose(
            np.asarray(lse)[:, :, shift:],
            np.asarray(ref_lse)[:, :, shift:],
            rtol=2e-4, atol=2e-4, err_msg=f"shift={shift}",
        )
        if shift:
            assert np.asarray(lse)[:, :, 0].max() < -1e29

    # shift=0 must equal the plain causal path bit-for-bit semantics.
    out_s0, lse_s0 = flash_attention_with_lse(
        q, k, v, causal=True, causal_shift=jnp.asarray(0, jnp.int32)
    )
    out_plain, lse_plain = flash_attention_with_lse(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out_s0), np.asarray(out_plain), rtol=1e-6, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(lse_s0), np.asarray(lse_plain), rtol=1e-6, atol=1e-6
    )

    with pytest.raises(ValueError, match="causal_shift"):
        flash_attention_with_lse(
            q, k, v, causal=False, causal_shift=jnp.asarray(1, jnp.int32)
        )
