"""Hierarchical KV cache: page codecs, the host-DRAM spill tier, and
the spill -> evict -> readmit serving path (ISSUE 14 acceptance).

The structural pins: lossless spill/readmit roundtrips are bit-exact
(a greedy stream whose prefix pages were evicted to the host tier and
readmitted equals an uninterrupted run), readmits count
``paged.prefix_hits``, spill work respects the per-tick budget, lossy
COLD codecs only ever see rc=0 spilled pages (never live-slot state),
and the whole thing composes with int8 pools, tp=2 head sharding,
speculative mode and the disaggregated wire.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.config import (
    CacheTierConfig,
    DisaggConfig,
    ParallelConfig,
    SpeculativeConfig,
)
from adapt_tpu.models.transformer_lm import lm_tiny
from adapt_tpu.ops.quantize import (
    LOSSLESS_PAGE_CODECS,
    PAGE_CODECS,
    decode_page,
    encode_page,
    page_codec_roundtrip,
)
from adapt_tpu.parallel.sharding import fetch_head_shards
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.runtime.disagg import (
    DisaggServer,
    HandoffError,
    PrefillWorker,
    pack_handoff,
    unpack_handoff,
    loopback,
)
from adapt_tpu.runtime.paged import HostKVTier, Pager
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.tracing import global_flight_recorder

VOCAB = 37
PAGE = 8
STEPS = 8


@pytest.fixture(scope="module")
def lm_setup():
    lm = lm_tiny(vocab=VOCAB, max_len=64)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


def _mk(lm, variables, pool_pages, tier=None, dtype="native", **kw):
    kws = dict(
        slots=1, chunk=4, kv_layout="paged", page_size=PAGE,
        pool_pages=pool_pages, kv_cache_dtype=dtype,
    )
    kws.update(kw)
    if tier is not None:
        kws["cache_tier"] = tier
    return ContinuousBatcher(lm, variables, **kws)


def _prompts(seed=0, n=4, size=2 * PAGE + 4):
    rng = np.random.RandomState(seed)
    A = rng.randint(0, VOCAB, size=size).astype(np.int32)
    flood = [
        rng.randint(0, VOCAB, size=size).astype(np.int32)
        for _ in range(n)
    ]
    return A, flood


def _evict_then_rereference(bat, A, flood):
    """Register A's prefix pages, flood-evict them, re-reference A.
    Returns A's second-reference stream."""
    bat.submit(A, STEPS)
    bat.run()
    for p in flood:
        bat.submit(p, STEPS)
    bat.run()
    rid = bat.submit(A, STEPS)
    return bat.run()[rid]


def _reference_stream(lm, variables, A, flood, **kw):
    """The uninterrupted run: big pool, same traffic — A's second
    reference is an ordinary HBM prefix hit."""
    ref = _mk(lm, variables, 64, **kw)
    try:
        return _evict_then_rereference(ref, A, flood)
    finally:
        ref.close()


# -- page codecs -------------------------------------------------------------


@pytest.mark.parametrize("codec", PAGE_CODECS)
@pytest.mark.parametrize(
    "dtype", [np.float32, np.int8, np.int32]
)
def test_page_codec_roundtrip_shapes(codec, dtype):
    rng = np.random.RandomState(0)
    x = (rng.randn(2, 3, 8, 16) * 3).astype(dtype)
    y = page_codec_roundtrip(x, codec)
    assert y.shape == x.shape and y.dtype == x.dtype
    if codec in LOSSLESS_PAGE_CODECS:
        np.testing.assert_array_equal(y, x)
    elif not np.issubdtype(np.dtype(dtype), np.floating):
        # Lossy on integer arrays degrades to lossless packing — the
        # guard that keeps lossy tiers away from already-quantized
        # int8 value planes and prompt ids.
        np.testing.assert_array_equal(y, x)
        _, meta = encode_page(x, codec)
        assert meta["codec"] == "lz"
    else:
        # Bounded error: zfp keeps 10 mantissa bits (rel err ~2^-11);
        # int8/int4 are the per-vector absmax lattices.
        err = np.abs(y.astype(np.float64) - x.astype(np.float64))
        amax = np.abs(x).max(axis=-1, keepdims=True)
        bound = {"zfp": 2.0**-10, "int8": 1.0 / 127, "int4": 1.0 / 7}[
            codec
        ]
        assert (err <= amax * bound + 1e-6).all()


def test_page_codec_meta_and_errors():
    x = np.zeros((4, 16), np.float32)
    payload, meta = encode_page(x, "lz")
    assert len(payload) < meta["raw_nbytes"]  # zeros compress
    np.testing.assert_array_equal(decode_page(payload, meta), x)
    with pytest.raises(ValueError):
        encode_page(x, "snappy")
    with pytest.raises(ValueError):
        encode_page(np.zeros((4, 15), np.float32), "int4")  # odd lane


# -- the host tier (unit) ----------------------------------------------------


def _blocks(rng, kvh=2, hd=4, quant=False):
    def member():
        if quant:
            return (
                rng.randint(-127, 127, (kvh, PAGE, hd)).astype(np.int8),
                rng.rand(kvh, PAGE, 1).astype(np.float32),
            )
        return rng.randn(kvh, PAGE, hd).astype(np.float32)

    return [(member(), member()) for _ in range(2)]


def test_host_tier_warm_cold_demotion_and_drop():
    cfg = CacheTierConfig(
        host_capacity_pages=4, warm_capacity_pages=2, cold_codec="int8"
    )
    tier = HostKVTier(cfg)
    rng = np.random.RandomState(0)
    pages = {}
    for i in range(6):
        key = b"k%d" % i
        pages[key] = _blocks(rng)
        tier.put(key, pages[key])
    st = tier.stats()
    assert st.pages == 4 and st.warm == 2 and st.cold == 2
    assert st.dropped == 2 and st.spilled == 6
    # Warm readmits bit-exact; cold went through the lossy codec.
    for k, v in zip(jax.tree.leaves(pages[b"k5"]),
                    jax.tree.leaves(tier.get(b"k5"))):
        np.testing.assert_array_equal(k, v)
    cold = tier.get(b"k3")
    for k, v in zip(jax.tree.leaves(pages[b"k3"]),
                    jax.tree.leaves(cold)):
        assert v.shape == k.shape and v.dtype == k.dtype
        assert np.allclose(k, v, atol=0.1)
    assert tier.get(b"k0") is None  # dropped off the cold end
    assert not tier.contains(b"k0") and tier.contains(b"k4")


def test_host_tier_quantized_members_and_saved_bytes():
    """int8-pool pages carry (values, scales) members; lossy cold
    codecs must pass the int8 value plane through bit-exact."""
    cfg = CacheTierConfig(
        host_capacity_pages=2, warm_capacity_pages=0, cold_codec="int4"
    )
    tier = HostKVTier(cfg)
    rng = np.random.RandomState(1)
    blocks = _blocks(rng, quant=True)
    tier.put(b"q", blocks)
    got = tier.get(b"q")
    for (k, v), (gk, gv) in zip(blocks, got):
        # value planes (int8) are bit-exact even under a lossy codec
        np.testing.assert_array_equal(k[0], gk[0])
        np.testing.assert_array_equal(v[0], gv[0])
        # scale planes (f32) may quantize, but keep shape/dtype
        assert gk[1].dtype == np.float32 and gk[1].shape == k[1].shape


def test_host_tier_disk_backing(tmp_path):
    cfg = CacheTierConfig(
        host_capacity_pages=1, warm_capacity_pages=1,
        disk_dir=str(tmp_path),
    )
    tier = HostKVTier(cfg)
    rng = np.random.RandomState(2)
    a, b = _blocks(rng), _blocks(rng)
    tier.put(b"a", a)
    tier.put(b"b", b)  # demotes "a" past capacity -> disk, not dropped
    st = tier.stats()
    assert st.dropped == 0 and st.disk == 1 and st.pages == 1
    assert tier.contains(b"a")
    for k, v in zip(jax.tree.leaves(a), jax.tree.leaves(tier.get(b"a"))):
        np.testing.assert_array_equal(k, v)


def test_pager_evict_hook_and_residency():
    p = Pager(4, 1, 4)
    seen = []
    p.evict_hook = lambda page, key: seen.append(key)
    p.adopt_cached([b"a", b"b", b"c"])
    assert p.resident(b"a") and [k for _, k in p.cached_pages()] == [
        b"a", b"b", b"c",
    ]
    p.evict_cached(1)  # sweep fires the hook
    assert seen == [b"a"] and not p.resident(b"a")
    p.alloc(0, 2)  # 0 free -> demand eviction fires it too
    assert seen == [b"a", b"b"]
    assert p.resident(b"c")


def test_fetch_head_shards_matches_logical(sim_mesh):
    from adapt_tpu.parallel.sharding import kv_head_sharding

    mesh = sim_mesh(2)
    x = jnp.arange(3 * 4 * 8 * 2, dtype=jnp.float32).reshape(3, 4, 8, 2)
    xs = jax.device_put(x, kv_head_sharding(mesh, "tp"))
    got = fetch_head_shards(xs, 1)
    np.testing.assert_array_equal(got, np.asarray(x[1]))


def test_cache_tier_requires_paged(lm_setup):
    lm, variables = lm_setup
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(
            lm, variables, slots=1, kv_layout="slots",
            cache_tier=CacheTierConfig(),
        )


# -- the serving path --------------------------------------------------------


@pytest.mark.parametrize("dtype", ["native", "int8"])
def test_spill_evict_readmit_bit_identical(lm_setup, dtype):
    """THE acceptance pin: flood pressure evicts A's registered prefix
    pages into the host tier; A's re-reference readmits them through
    the adopt_cached landing path, counts prefix hits, and the stream
    equals the uninterrupted big-pool run token-for-token — the pool
    partition staying exact throughout."""
    lm, variables = lm_setup
    A, flood = _prompts()
    want = _reference_stream(lm, variables, A, flood, dtype=dtype)
    tier = CacheTierConfig(
        spill_pages_per_tick=16, readmit_pages_per_tick=16
    )
    bat = _mk(lm, variables, 12, tier=tier, dtype=dtype)
    kinds0 = dict(global_flight_recorder().kind_counts())
    bat.submit(A, STEPS)
    bat.run()
    for p in flood:
        bat.submit(p, STEPS)
    bat.run()
    st = bat.stats()
    assert st["tier_spilled"] > 0, "flood never spilled"
    hits0 = st["prefix_hits"]
    rid = bat.submit(A, STEPS)
    got = bat.run()[rid]
    np.testing.assert_array_equal(got, want)
    st = bat.stats()
    assert st["tier_readmitted"] >= 1
    assert st["prefix_hits"] - hits0 >= st["tier_readmitted"]
    # Pool partition exact with the tier attached (pages_free counts
    # evictable cached pages — the gauges partition instead).
    alloc = st["pool_pages"] - 1
    assert st["pages_in_use"] + (st["pages_free"] - st["pages_cached"]) \
        + st["pages_cached"] == alloc
    kinds = global_flight_recorder().kind_counts()
    assert kinds.get("kv_spill", 0) > kinds0.get("kv_spill", 0)
    assert kinds.get("kv_readmit", 0) > kinds0.get("kv_readmit", 0)
    bat.close()


def test_spill_budget_respected_and_drops_counted(lm_setup):
    """A spill budget of 1/tick bounds tier work: no tick spills more
    than one page, and evictions past the budget count dropped."""
    lm, variables = lm_setup
    A, flood = _prompts(n=6)
    tier = CacheTierConfig(
        spill_pages_per_tick=1, readmit_pages_per_tick=4,
        # Neutralize the proactive sweep (need = cached - alloc <= 0),
        # so every spill is a demand capture at eviction — the budget
        # path under test.
        spill_watermark=1.0, spill_low_watermark=1.0,
    )
    bat = _mk(lm, variables, 12, tier=tier)
    bat.submit(A, STEPS)
    bat.run()
    last = bat.stats()["tier_spilled"]
    for p in flood:
        bat.submit(p, STEPS)
        while bat.tick() or bat.stats()["queued"]:
            s = bat.stats()["tier_spilled"]
            assert s - last <= 1, "tick spilled past the budget"
            last = s
    st = bat.stats()
    assert st["tier_spilled"] >= 1
    assert st["tier_dropped"] >= 1, (
        "evictions past a 1-page budget must count dropped"
    )
    bat.close()


def test_live_pages_never_spill(lm_setup):
    """Only rc=0 LRU pages ever reach the tier (the invariant that
    keeps lossy cold codecs away from live decode state): while a
    request holds its prompt pages, their keys stay out of the host
    tier even under the most aggressive watermark."""
    lm, variables = lm_setup
    tier = CacheTierConfig(
        spill_watermark=0.0, spill_low_watermark=0.0,
        spill_pages_per_tick=64,
    )
    bat = _mk(lm, variables, 16, tier=tier, slots=1)
    rng = np.random.RandomState(3)
    A = rng.randint(0, VOCAB, size=2 * PAGE + 2).astype(np.int32)
    bat.submit(A, 24)
    for _ in range(3):
        bat.tick()
    # Mid-request: prompt pages are rc>0 and registered; the sweep ran
    # every tick at watermark 0, yet none of A's keys may be host-side.
    assert bat.stats()["active"] == 1
    for j in range(2):
        key = Pager.prefix_key(A, (j + 1) * PAGE)
        assert not bat._tier.contains(key)
    bat.run()
    # Retired: the pages are rc=0 LRU now — the sweep may take them.
    bat.tick()
    assert bat.stats()["tier_spilled"] >= 1
    bat.close()


def test_prefix_cached_reads_the_hierarchy(lm_setup):
    lm, variables = lm_setup
    A, flood = _prompts()
    tier = CacheTierConfig(
        spill_pages_per_tick=16, readmit_pages_per_tick=16
    )
    bat = _mk(lm, variables, 12, tier=tier)
    assert bat.prefix_cached(A) == 0
    bat.submit(A, STEPS)
    bat.run()
    assert bat.prefix_cached(A) == 2  # HBM-resident
    for p in flood:
        bat.submit(p, STEPS)
    bat.run()
    # Evicted from HBM but host-resident: still servable.
    assert bat.stats()["tier_spilled"] > 0
    assert bat.prefix_cached(A) == 2
    bat.close()


def test_cold_codec_stream_agreement(lm_setup):
    """Warm capacity 0 demotes every spill through the lossy int8
    page codec; the readmitted stream's top-1 agreement vs the
    uncompressed reference holds the >= 0.95 bar (the int4 pools'
    bar)."""
    lm, variables = lm_setup
    A, flood = _prompts()
    want = _reference_stream(lm, variables, A, flood)
    tier = CacheTierConfig(
        host_capacity_pages=64, warm_capacity_pages=0,
        cold_codec="int8", spill_pages_per_tick=16,
        readmit_pages_per_tick=16,
    )
    bat = _mk(lm, variables, 12, tier=tier)
    got = _evict_then_rereference(bat, A, flood)
    assert bat.stats()["tier_readmitted"] >= 1
    n = min(len(got), len(want))
    assert n > 0
    agreement = float((got[:n] == want[:n]).sum()) / n
    assert agreement >= 0.95, agreement
    bat.close()


# -- composition -------------------------------------------------------------


@pytest.mark.slow
def test_tp2_spill_readmit_bit_identical(lm_setup, sim_mesh):
    """tp=2 head sharding composes: spill assembles per-shard host
    pieces (fetch_head_shards), readmit places per-shard slices
    (KVHandoffPlan) — streams stay bit-identical to the uninterrupted
    tp=2 run."""
    lm, variables = lm_setup
    mesh = sim_mesh(2)
    A, flood = _prompts()
    kw = dict(mesh=mesh, parallel=ParallelConfig(tp=2))
    want = _reference_stream(lm, variables, A, flood, **kw)
    tier = CacheTierConfig(
        spill_pages_per_tick=16, readmit_pages_per_tick=16
    )
    bat = _mk(lm, variables, 12, tier=tier, **kw)
    got = _evict_then_rereference(bat, A, flood)
    np.testing.assert_array_equal(got, want)
    assert bat.stats()["tier_readmitted"] >= 1
    bat.close()


@pytest.mark.slow
def test_speculative_spill_readmit_bit_identical(lm_setup):
    """Speculative mode composes (self-draft, perfect acceptance):
    the readmitted prefix feeds the same draft+verify tick and the
    stream equals the uninterrupted speculative run."""
    lm, variables = lm_setup
    A, flood = _prompts()
    kw = dict(
        draft_lm=lm, draft_variables=variables,
        speculative=SpeculativeConfig(draft_k=3),
    )
    want = _reference_stream(lm, variables, A, flood, **kw)
    tier = CacheTierConfig(
        spill_pages_per_tick=16, readmit_pages_per_tick=16
    )
    bat = _mk(lm, variables, 12, tier=tier, **kw)
    got = _evict_then_rereference(bat, A, flood)
    np.testing.assert_array_equal(got, want)
    assert bat.stats()["tier_readmitted"] >= 1
    bat.close()


def test_wire_codec_roundtrip_and_crc_on_compressed():
    """MSG_KV_PAGES with a wire codec: lz roundtrips bit-exact, lossy
    codecs keep int tensors (prompt) exact, and the crc verifies the
    COMPRESSED payload — a flipped wire bit raises before any decode."""
    from adapt_tpu.runtime.disagg import KVHandoff
    from adapt_tpu.comm.framing import frame_parts, parse_frame

    rng = np.random.RandomState(3)

    def member():
        return rng.rand(3, 2, PAGE, 4).astype(np.float32)

    h = KVHandoff(
        req_id=7,
        prompt=rng.randint(0, VOCAB, size=3 * PAGE + 3).astype(np.int32),
        page_size=PAGE, n_pages=3, quantized=False,
        blocks=[(member(), member()) for _ in range(2)],
    )
    got = unpack_handoff(loopback(pack_handoff(h, wire_codec="lz")))
    np.testing.assert_array_equal(got.prompt, h.prompt)
    for (hk, hv), (gk, gv) in zip(h.blocks, got.blocks):
        np.testing.assert_array_equal(hk, gk)
        np.testing.assert_array_equal(hv, gv)
    lossy = unpack_handoff(loopback(pack_handoff(h, wire_codec="int8")))
    np.testing.assert_array_equal(lossy.prompt, h.prompt)  # int: exact
    assert np.allclose(lossy.blocks[0][0], h.blocks[0][0], atol=0.02)
    # crc runs on the compressed payload: flip a late (payload) byte.
    msg = pack_handoff(h, wire_codec="lz")
    wire = bytearray(b"".join(frame_parts(msg)))
    wire[-5] ^= 0xFF
    with pytest.raises((HandoffError, ConnectionError)):
        unpack_handoff(parse_frame(memoryview(wire)[8:]))


def test_disagg_wire_codec_and_raw_bytes_counter(lm_setup):
    """DisaggServer + tier-enabled decode + lz wire codec: streams
    stay bit-identical to the collocated path, and the wire records
    BOTH post-codec (handoff_bytes) and raw (handoff_bytes_raw)
    bytes."""
    lm, variables = lm_setup
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, VOCAB, size=37).astype(np.int32)
    ref = _mk(lm, variables, 64, slots=2)
    rid = ref.submit(prompt, 10)
    want = ref.run()[rid]
    ref.close()
    tier = CacheTierConfig(wire_codec="lz")
    decode = _mk(lm, variables, 64, tier=tier, slots=2)
    worker = PrefillWorker(
        lm, variables, page_size=PAGE, prefill_chunk=2 * PAGE
    )
    srv = DisaggServer(
        decode, worker,
        DisaggConfig(prompt_threshold=2 * PAGE,
                     busy_prompt_threshold=2 * PAGE),
    )
    assert srv.wire_codec == "lz"  # inherited from the tier config
    c0 = global_metrics().snapshot()["counters"]
    sid = srv.submit(prompt, 10)
    got = srv.run()[sid]
    np.testing.assert_array_equal(got, want)
    assert srv.disaggregated == 1
    c1 = global_metrics().snapshot()["counters"]
    wire = c1.get("disagg.handoff_bytes", 0) - c0.get(
        "disagg.handoff_bytes", 0
    )
    raw = c1.get("disagg.handoff_bytes_raw", 0) - c0.get(
        "disagg.handoff_bytes_raw", 0
    )
    assert wire > 0 and raw > 0
    srv.close()
    decode.close()
