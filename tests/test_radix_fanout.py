"""Radix prefix cache fan-out and temperature>0 speculation (the
ISSUE-18 surface): ``submit_fanout(prompt, n)`` must be invisible in
outputs — every greedy sibling bit-identical to a solo ``generate()``,
every sampled sibling equal to a serial submit under its split of the
caller's key — while the pager books exactly n-1 copy-on-write forks
and drains balanced. The speculative-sampling verify (accept/reject +
residual resample) rides along: top_k=1 pins it to the greedy stream
with zero statistics, and a seed-pinned distributional gate checks
losslessness IN DISTRIBUTION at real temperatures."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.config import SpeculativeConfig
from adapt_tpu.models.transformer_lm import (
    generate,
    lm_tiny,
    transformer_lm,
)
from adapt_tpu.runtime.continuous import ContinuousBatcher


@pytest.fixture(scope="module")
def lm_setup():
    lm = lm_tiny(vocab=37, max_len=48)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture(scope="module")
def spec_setup():
    # The test_continuous_spec target: deliberately SMALLER than
    # lm_tiny — losslessness is a scheduling property, not a
    # model-size one, and tier-1 wall time is the budget (ROADMAP.md).
    lm = transformer_lm(37, 32, 2, 2, 64, max_len=48, name="spec_target")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture(scope="module")
def draft_setup():
    # Same vocab, smaller independent model: a REAL draft whose
    # proposals are mostly wrong (adversarial acceptance).
    draft = transformer_lm(37, 16, 1, 1, 32, max_len=48, name="draft")
    variables = draft.graph.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32)
    )
    return draft, variables


def _solo(lm, variables, prompt, steps, **kw):
    return np.asarray(
        generate(lm, variables, jnp.asarray(prompt)[None], steps, **kw)
    )[0]


# -- copy-on-write fan-out ----------------------------------------------------


def test_fanout_greedy_paged_bit_identical_and_cow_books(lm_setup):
    """``submit_fanout(prompt, n)`` on a paged batcher: every greedy
    sibling's stream is bit-identical to a solo generate() of the same
    prompt, the group books n-1 copy-on-write forks (siblings after
    the first fork the shared last prompt page instead of re-running
    the suffix pass), and the pool drains balanced — no leaked group
    claims, partition exact."""
    lm, variables = lm_setup
    rng = np.random.RandomState(21)
    prompt = rng.randint(0, 37, size=19).astype(np.int32)  # 2 full pages
    bat = ContinuousBatcher(
        lm, variables, slots=4, chunk=4, kv_layout="paged", page_size=8
    )
    rids = bat.submit_fanout(prompt, 3, 5)
    assert len(rids) == len(set(rids)) == 3
    out = bat.run()
    want = _solo(lm, variables, prompt, 5)
    for j, r in enumerate(rids):
        np.testing.assert_array_equal(out[r], want, err_msg=f"sibling {j}")
    st = bat.stats()
    assert st["cow_forks"] == 2
    assert st["fanout_groups"] == 0 and st["pages_in_use"] == 0
    # free already counts the evictable rc=0 cached pages.
    assert st["pages_free"] == st["pool_pages"] - 1


def test_fanout_dense_and_width_one_degrade_to_serial(lm_setup):
    """Dense layouts and n == 1 take the plain submit path: same
    streams, no fan-out group machinery (and no pager to fork)."""
    lm, variables = lm_setup
    prompt = np.asarray([5, 6, 7, 8], np.int32)
    bat = ContinuousBatcher(lm, variables, slots=2, chunk=4)
    rids = bat.submit_fanout(prompt, 2, 4)
    paged = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged", page_size=8
    )
    rids.append(paged.submit_fanout(prompt, 1, 4)[0])
    want = _solo(lm, variables, prompt, 4)
    out = bat.run()
    out.update(paged.run())
    for r in rids:
        np.testing.assert_array_equal(out[r], want)
    assert bat.stats().get("cow_forks", 0) == 0
    assert paged.stats()["cow_forks"] == 0
    assert paged.stats()["fanout_groups"] == 0


def test_fanout_sampled_splits_rng_per_sibling(lm_setup):
    """temperature > 0 fan-out: each sibling samples under its own
    split of the caller's key (parallel-sampling semantics — streams
    diverge by design) and equals a serial submit with that split.
    Sampled siblings run the ordinary suffix pass (divergent first
    tokens cannot reuse a forked greedy commit), so no CoW forks are
    booked; only the full prefix pages are shared. Width >= 1 and the
    rng requirement are validated synchronously."""
    lm, variables = lm_setup
    rng = np.random.RandomState(22)
    prompt = rng.randint(0, 37, size=19).astype(np.int32)
    key = jax.random.PRNGKey(11)
    bat = ContinuousBatcher(
        lm, variables, slots=3, chunk=4, kv_layout="paged", page_size=8
    )
    rids = bat.submit_fanout(prompt, 3, 5, temperature=0.9, rng=key)
    out = bat.run()
    for j, (r, k) in enumerate(zip(rids, jax.random.split(key, 3))):
        want = _solo(lm, variables, prompt, 5, temperature=0.9, rng=k)
        np.testing.assert_array_equal(out[r], want, err_msg=f"sibling {j}")
    st = bat.stats()
    assert st["cow_forks"] == 0
    assert st["fanout_groups"] == 0 and st["pages_in_use"] == 0
    with pytest.raises(ValueError, match="rng"):
        bat.submit_fanout(prompt, 2, 3, temperature=0.5)
    with pytest.raises(ValueError, match="width"):
        bat.submit_fanout(prompt, 0, 3)


def test_fanout_cancel_queued_sibling_keeps_group_books_clean(lm_setup):
    """Cancelling a still-queued sibling shrinks the group without
    wedging it: the survivors stream bit-identically (the second
    sibling still forks), the cancelled one returns empty, and the
    group's page claim is released when the last survivor admits."""
    lm, variables = lm_setup
    rng = np.random.RandomState(23)
    prompt = rng.randint(0, 37, size=19).astype(np.int32)
    bat = ContinuousBatcher(
        lm, variables, slots=1, chunk=4, kv_layout="paged", page_size=8
    )
    rids = bat.submit_fanout(prompt, 3, 4)
    bat.tick()  # admit sibling 0; 1 and 2 queue behind the one slot
    assert bat.cancel(rids[2])
    out = bat.run()
    want = _solo(lm, variables, prompt, 4)
    np.testing.assert_array_equal(out[rids[0]], want)
    np.testing.assert_array_equal(out[rids[1]], want)
    assert out[rids[2]].shape == (0,)
    st = bat.stats()
    assert st["cow_forks"] == 1  # sibling 1 forked before the group died
    assert st["fanout_groups"] == 0 and st["pages_in_use"] == 0


# -- temperature>0 speculation ------------------------------------------------


@pytest.mark.parametrize("layout", ["slots", "paged"])
def test_spec_sampling_topk1_matches_greedy(spec_setup, draft_setup, layout):
    """Deterministic end-to-end probe of the temperature>0 verify:
    top_k=1 shapes the target to a point mass on its argmax, so the
    speculative-SAMPLING path (accept u < p_t/p_d, residual resample
    on reject) must commit exactly the greedy stream — the adversarial
    draft makes most proposals miss the argmax, so the reject +
    residual-resample branch is exercised with zero statistics."""
    lm, variables = spec_setup
    draft, dvars = draft_setup
    rng = np.random.RandomState(17)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (4, 9, 6)]
    kw = dict(kv_layout="paged", page_size=8) if layout == "paged" else {}
    bat = ContinuousBatcher(
        lm, variables, slots=2, draft_lm=draft, draft_variables=dvars,
        speculative=SpeculativeConfig(draft_k=3), **kw,
    )
    ids = {
        bat.submit(
            p, 8, temperature=0.7, top_k=1, rng=jax.random.PRNGKey(i)
        ): p
        for i, p in enumerate(prompts)
    }
    out = bat.run()
    for rid, p in ids.items():
        np.testing.assert_array_equal(
            out[rid], _solo(lm, variables, p, 8), err_msg=layout
        )


@pytest.mark.statistical
def test_spec_sampling_statistical(spec_setup):
    """The seed-pinned distributional gate for temperature>0
    speculation: over many submits of one prompt, the spec batcher's
    token marginal matches a non-spec batcher's (loose total-variation
    bound — lossless IN DISTRIBUTION, not bit-identical), while the
    self-draft's acceptance stays above 1/draft_k, i.e. each verify
    pass commits MORE than the one correction token a spec-less tick
    would (the whole point of speculating at temperature>0)."""
    lm, variables = spec_setup
    prompt = np.asarray([3, 1, 4, 1], np.int32)
    # temp 0.3 concentrates the tiny model's target enough that the
    # self-draft's argmax proposals carry real target mass (acceptance
    # ~0.5; at temp 0.5 this model measures ~0.2 and each verify pass
    # commits barely more than its correction token) while leaving
    # several tokens of support for the distributional comparison.
    steps, m, draft_k, temp = 3, 72, 4, 0.3
    counts = {}
    for arm in ("nonspec", "spec"):
        extra = (
            dict(
                draft_lm=lm, draft_variables=variables,
                speculative=SpeculativeConfig(draft_k=draft_k),
            )
            if arm == "spec"
            else {}
        )
        bat = ContinuousBatcher(lm, variables, slots=4, **extra)
        hist = np.zeros(37, np.int64)
        for lo in range(0, m, 12):  # batches: stay inside queue bounds
            rids = [
                bat.submit(
                    prompt, steps, temperature=temp,
                    rng=jax.random.PRNGKey(i),
                )
                for i in range(lo, min(lo + 12, m))
            ]
            out = bat.run()
            for r in rids:
                assert len(out[r]) == steps
                np.add.at(hist, out[r], 1)
        counts[arm] = hist
        if arm == "spec":
            acc = bat.stats()["spec_acceptance"]
            assert acc > 1.0 / draft_k, acc
    p = counts["nonspec"] / counts["nonspec"].sum()
    q = counts["spec"] / counts["spec"].sum()
    tvd = 0.5 * float(np.abs(p - q).sum())
    # Loose bound: ~2x the pinned seeds' sampling noise. A failure
    # after an intentional sampling change means re-deriving the
    # pinned expectation, not loosening this (conftest marker note).
    assert tvd < 0.35, tvd
