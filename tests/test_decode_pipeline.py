"""Fault-tolerant decode sessions: the PipelinedDecoder must emit
exactly what single-program ``generate()`` emits — including when a
stage worker crashes or hangs MID-DECODE and the session replays
committed tokens to rebuild the lost stage's KV caches on a spare."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.config import FaultConfig
from adapt_tpu.models.transformer_lm import generate, lm_tiny
from adapt_tpu.runtime.decode_pipeline import PipelinedDecoder
from adapt_tpu.utils.metrics import global_metrics

FAST = FaultConfig(
    lease_ttl_s=0.5,
    heartbeat_s=0.1,
    task_deadline_s=3.0,
    watchdog_period_s=0.05,
    max_retries=4,
)


@pytest.fixture(scope="module")
def lm_setup():
    lm = lm_tiny(vocab=59, max_len=32)
    prompt = jax.random.randint(jax.random.PRNGKey(0), (4, 6), 0, 59)
    variables = lm.graph.init(jax.random.PRNGKey(1), prompt)
    return lm, variables, prompt


def test_no_fault_matches_generate(devices, lm_setup):
    lm, variables, prompt = lm_setup
    want = np.asarray(generate(lm, variables, prompt, 6))
    with PipelinedDecoder(
        lm, variables, [2], devices=devices[:3], fault=FAST
    ) as dec:
        got = dec.generate(prompt, 6)
    np.testing.assert_array_equal(got, want)


def test_sampled_matches_generate(devices, lm_setup):
    lm, variables, prompt = lm_setup
    kw = dict(temperature=0.8, top_k=9, rng=jax.random.PRNGKey(3))
    want = np.asarray(generate(lm, variables, prompt, 5, **kw))
    with PipelinedDecoder(
        lm, variables, [2], devices=devices[:3], fault=FAST
    ) as dec:
        got = dec.generate(prompt, 5, **kw)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mode", ["crash", "hang"])
def test_worker_kill_mid_decode_replays_and_matches(devices, lm_setup, mode):
    """The flagship failure: a stage dies AFTER several tokens committed.
    The session must detect it (deadline), rebind the stage to a spare,
    rebuild its caches by replaying the committed tokens, and finish with
    output identical to the no-fault oracle — exactly-once, no token
    duplicated or lost."""
    lm, variables, prompt = lm_setup
    steps = 8
    want = np.asarray(generate(lm, variables, prompt, steps))
    global_metrics().reset()
    killed = []

    with PipelinedDecoder(
        lm, variables, [2], devices=devices[:3], fault=FAST
    ) as dec:

        def on_token(m, s):
            # Kill stage 1 once, after microbatch 0 commits its 3rd token
            # — mid-decode, caches already hold replayed positions.
            if not killed and m == 0 and s == 2:
                killed.append(mode)
                dec.kill_worker(1, mode=mode)

        got = dec.generate(prompt, steps, on_token=on_token)

    assert killed, "kill hook never fired"
    np.testing.assert_array_equal(got, want)
    counters = global_metrics().snapshot()["counters"]
    assert counters.get("decode.recoveries", 0) >= 1


def test_kill_during_eos_sampling_session(devices, lm_setup):
    """Recovery composes with the sampling + EOS knobs (per-row keys make
    the replayed session's draws identical)."""
    lm, variables, prompt = lm_setup
    steps = 7
    kw = dict(temperature=1.1, top_k=13, rng=jax.random.PRNGKey(5))
    want = np.asarray(generate(lm, variables, prompt, steps, **kw))
    eos = int(want[0, 1])  # some row hits EOS mid-stream
    want_eos = np.asarray(
        generate(lm, variables, prompt, steps, eos_id=eos, **kw)
    )
    killed = []

    with PipelinedDecoder(
        lm, variables, [1, 3], devices=devices[:4], fault=FAST
    ) as dec:

        def on_token(m, s):
            if not killed and s == 3:
                killed.append(m)
                dec.kill_worker(0, mode="crash")

        got = dec.generate(
            prompt, steps, eos_id=eos, on_token=on_token, **kw
        )

    assert killed
    np.testing.assert_array_equal(got, want_eos)


def test_int8_stage_caches_survive_kill(devices, lm_setup):
    """int8 stage caches + a mid-decode crash: replay rebuilds the
    quantized caches identically, so output still equals
    generate(kv_cache_dtype="int8")."""
    lm, variables, prompt = lm_setup
    want = np.asarray(
        generate(lm, variables, prompt, 7, kv_cache_dtype="int8")
    )
    killed = []
    with PipelinedDecoder(
        lm, variables, [2], devices=devices[:3], fault=FAST,
        kv_cache_dtype="int8",
    ) as dec:

        def on_token(m, s):
            if not killed and s == 3:
                killed.append(1)
                dec.kill_worker(0, mode="crash")

        got = dec.generate(prompt, 7, on_token=on_token)
    assert killed
    np.testing.assert_array_equal(got, want)


def test_rejects_bad_boundaries(devices, lm_setup):
    lm, variables, _ = lm_setup
    for bad in ([3, 1], [0], [4], [2, 2]):
        with pytest.raises(ValueError, match="boundaries"):
            PipelinedDecoder(
                lm, variables, bad, devices=devices[:2], fault=FAST
            )


def test_ragged_prompts_survive_kill(devices, lm_setup):
    """Ragged batches (right-padded + prompt_lengths) through the decode
    session, with a crash mid-decode: the replay must rebuild the
    left-aligned masked caches and still match generate() row for row."""
    lm, variables, _ = lm_setup
    lens = [2, 5, 3, 6]
    s0 = max(lens)
    prompt = jax.random.randint(jax.random.PRNGKey(9), (4, s0), 0, 59)
    lengths = jnp.asarray(lens)
    want = np.asarray(
        generate(lm, variables, prompt, 6, prompt_lengths=lengths)
    )
    killed = []
    with PipelinedDecoder(
        lm, variables, [2], devices=devices[:3], fault=FAST
    ) as dec:

        def on_token(m, s):
            if not killed and s == 2:
                killed.append(1)
                dec.kill_worker(1, mode="crash")

        got = dec.generate(
            prompt, 6, prompt_lengths=lengths, on_token=on_token
        )
    assert killed
    np.testing.assert_array_equal(got, want)


def test_ragged_int8_compose_under_kill(devices, lm_setup):
    """Ragged prompts AND int8 stage caches together (they compose: the
    vf mask must keep quantized left-pad slots out of every window, and
    replay must rebuild the quantized masked caches), plus a crash."""
    lm, variables, _ = lm_setup
    lens = [3, 6, 2, 4]
    s0 = max(lens)
    prompt = jax.random.randint(jax.random.PRNGKey(11), (4, s0), 0, 59)
    lengths = jnp.asarray(lens)
    want = np.asarray(
        generate(
            lm, variables, prompt, 5, prompt_lengths=lengths,
            kv_cache_dtype="int8",
        )
    )
    killed = []
    with PipelinedDecoder(
        lm, variables, [2], devices=devices[:3], fault=FAST,
        kv_cache_dtype="int8",
    ) as dec:

        def on_token(m, s):
            if not killed and s == 2:
                killed.append(1)
                dec.kill_worker(0, mode="crash")

        got = dec.generate(
            prompt, 5, prompt_lengths=lengths, on_token=on_token
        )
    assert killed
    np.testing.assert_array_equal(got, want)


def test_rejects_bad_microbatch_split(devices, lm_setup):
    lm, variables, prompt = lm_setup
    with PipelinedDecoder(
        lm, variables, [2], devices=devices[:2], fault=FAST
    ) as dec:
        with pytest.raises(ValueError, match="microbatch"):
            dec.generate(prompt, 4, num_microbatches=3)


def test_rejects_bad_kv_dtype(devices, lm_setup):
    lm, variables, _ = lm_setup
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        PipelinedDecoder(
            lm, variables, [2], devices=devices[:2], fault=FAST,
            kv_cache_dtype="int4",
        )


def test_top_p_matches_generate(devices, lm_setup):
    lm, variables, prompt = lm_setup
    kw = dict(temperature=1.0, top_p=0.65, rng=jax.random.PRNGKey(43))
    want = np.asarray(generate(lm, variables, prompt, 5, **kw))
    with PipelinedDecoder(
        lm, variables, [2], devices=devices[:3], fault=FAST
    ) as dec:
        got = dec.generate(prompt, 5, **kw)
    np.testing.assert_array_equal(got, want)


def test_gqa_matches_generate(devices):
    """GQA decode sessions: stage workers hold the smaller kv_heads
    caches; tokens (and replay-based recovery state) match generate()."""
    from adapt_tpu.models.transformer_lm import transformer_lm

    vocab = 37
    lm = transformer_lm(vocab=vocab, dim=32, depth=2, heads=4, mlp_dim=48,
                        max_len=32, kv_heads=2)
    prompt = jax.random.randint(jax.random.PRNGKey(80), (2, 5), 0, vocab)
    variables = lm.graph.init(jax.random.PRNGKey(81), prompt)
    want = np.asarray(generate(lm, variables, prompt, 6))
    with PipelinedDecoder(
        lm, variables, [1], devices=devices[:3], fault=FAST
    ) as dec:
        got = dec.generate(prompt, 6)
    np.testing.assert_array_equal(got, want)
