"""Continuous batching: requests served through the slot-based batcher
must emit token-for-token what single-program ``generate()`` emits for
each request ALONE — slot scheduling, bucketed prefill, admission order,
and lockstep ticking must be invisible in outputs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.models.transformer_lm import generate, lm_tiny
from adapt_tpu.runtime.continuous import ContinuousBatcher


@pytest.fixture(scope="module")
def lm_setup():
    lm = lm_tiny(vocab=37, max_len=48)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


def _solo(lm, variables, prompt, steps, **kw):
    return np.asarray(
        generate(lm, variables, jnp.asarray(prompt)[None], steps, **kw)
    )[0]


@pytest.mark.parametrize("chunk", [1, 8])
def test_staggered_greedy_requests_match_generate(lm_setup, chunk):
    """Requests of different lengths arriving at different times (some
    mid-decode of others) each match their solo generate() output —
    whether ticks run one step (fully reactive) or a compiled 8-step
    chunk (whose mid-chunk garbage tails must be invisible)."""
    lm, variables = lm_setup
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (3, 9, 5, 12, 7)]
    steps = [6, 4, 8, 3, 5]

    bat = ContinuousBatcher(lm, variables, slots=3, chunk=chunk)
    ids = {}
    for i in range(2):
        ids[bat.submit(prompts[i], steps[i])] = i
    bat.tick()
    bat.tick()
    for i in range(2, 5):  # arrive while the first two are mid-decode
        ids[bat.submit(prompts[i], steps[i])] = i
    out = bat.run()
    assert set(out) == set(ids)
    for rid, i in ids.items():
        want = _solo(lm, variables, prompts[i], steps[i])
        np.testing.assert_array_equal(out[rid], want, err_msg=f"req {i}")


def test_sampled_requests_match_generate(lm_setup):
    """Per-request key schedules reproduce generate()'s sampled streams
    even when greedy and sampled requests share the lockstep batch."""
    lm, variables = lm_setup
    p1 = np.asarray([1, 2, 3, 4], np.int32)
    p2 = np.asarray([5, 6, 7], np.int32)
    p3 = np.asarray([8, 9, 10, 11, 12], np.int32)
    bat = ContinuousBatcher(lm, variables, slots=2, top_k=5)
    r1 = bat.submit(p1, 6, temperature=0.9, rng=jax.random.PRNGKey(7))
    r2 = bat.submit(p2, 5)  # greedy, same batch
    r3 = bat.submit(p3, 4, temperature=1.3, rng=jax.random.PRNGKey(9))
    out = bat.run()
    np.testing.assert_array_equal(
        out[r1],
        _solo(lm, variables, p1, 6, temperature=0.9, top_k=5,
              rng=jax.random.PRNGKey(7)),
    )
    np.testing.assert_array_equal(out[r2], _solo(lm, variables, p2, 5))
    np.testing.assert_array_equal(
        out[r3],
        _solo(lm, variables, p3, 4, temperature=1.3, top_k=5,
              rng=jax.random.PRNGKey(9)),
    )


def test_eos_frees_slot_stream_matches_prefix(lm_setup):
    """EOS finishes a request early: the emitted stream equals
    generate()'s output up to and including the first EOS (generate pads
    with EOS after; a server frees the slot instead)."""
    lm, variables = lm_setup
    p = np.asarray([4, 8, 15], np.int32)
    full = _solo(lm, variables, p, 8)
    eos = int(full[1])  # the second greedy token -> finishes after 2
    padded = _solo(lm, variables, p, 8, eos_id=eos)
    bat = ContinuousBatcher(lm, variables, slots=2)
    rid = bat.submit(p, 8, eos_id=eos)
    out = bat.run()
    n = len(out[rid])
    assert out[rid][-1] == eos and eos not in out[rid][:-1]
    np.testing.assert_array_equal(out[rid], padded[:n])


def test_more_requests_than_slots(lm_setup):
    """Slots recycle: 7 requests drain through 2 slots."""
    lm, variables = lm_setup
    rng = np.random.RandomState(3)
    reqs = [rng.randint(0, 37, size=rng.randint(2, 10)).astype(np.int32)
            for _ in range(7)]
    bat = ContinuousBatcher(lm, variables, slots=2)
    ids = {bat.submit(p, 4): p for p in reqs}
    out = bat.run()
    assert set(out) == set(ids)
    for rid, p in ids.items():
        np.testing.assert_array_equal(
            out[rid], _solo(lm, variables, p, 4)
        )


def test_per_request_top_k_matches_generate(lm_setup):
    """Different top_k per request in ONE batch (traced per-row
    truncation): each stream equals its own generate(top_k=...) solo."""
    lm, variables = lm_setup
    p1 = np.asarray([1, 2, 3], np.int32)
    p2 = np.asarray([4, 5, 6, 7], np.int32)
    p3 = np.asarray([8, 9], np.int32)
    bat = ContinuousBatcher(lm, variables, slots=3)  # no default top_k
    r1 = bat.submit(p1, 5, temperature=0.8, top_k=3,
                    rng=jax.random.PRNGKey(21))
    r2 = bat.submit(p2, 5, temperature=1.1, top_k=12,
                    rng=jax.random.PRNGKey(22))
    r3 = bat.submit(p3, 5, temperature=0.9,  # untruncated
                    rng=jax.random.PRNGKey(23))
    out = bat.run()
    np.testing.assert_array_equal(
        out[r1], _solo(lm, variables, p1, 5, temperature=0.8, top_k=3,
                       rng=jax.random.PRNGKey(21)))
    np.testing.assert_array_equal(
        out[r2], _solo(lm, variables, p2, 5, temperature=1.1, top_k=12,
                       rng=jax.random.PRNGKey(22)))
    np.testing.assert_array_equal(
        out[r3], _solo(lm, variables, p3, 5, temperature=0.9,
                       rng=jax.random.PRNGKey(23)))


def test_int8_slot_caches_match_generate_int8(lm_setup):
    """Quantized slot caches reproduce generate(kv_cache_dtype="int8")
    exactly — same absmax-per-vector scheme, so the only difference is
    where the cache lives."""
    lm, variables = lm_setup
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (4, 7, 3)]
    bat = ContinuousBatcher(
        lm, variables, slots=2, kv_cache_dtype="int8", chunk=4
    )
    ids = {bat.submit(p, 6): p for p in prompts}
    out = bat.run()
    for rid, p in ids.items():
        want = _solo(lm, variables, p, 6, kv_cache_dtype="int8")
        np.testing.assert_array_equal(out[rid], want)


def test_validation(lm_setup):
    lm, variables = lm_setup
    bat = ContinuousBatcher(lm, variables, slots=2)
    with pytest.raises(ValueError, match="steps"):
        bat.submit(np.asarray([1], np.int32), 0)
    with pytest.raises(ValueError, match="max_len"):
        bat.submit(np.zeros(40, np.int32), 20)
    with pytest.raises(ValueError, match="rng"):
        bat.submit(np.asarray([1], np.int32), 2, temperature=0.5)
    with pytest.raises(ValueError, match="top_k"):
        ContinuousBatcher(lm, variables, slots=2, top_k=99)


def test_no_top_p_request_unaffected_by_nucleus_neighbor(lm_setup):
    """Regression: a sampled request WITHOUT top_p batched next to a
    nucleus request flows through the shared filter with p=1.0 — which
    must be an exact identity (f32 cumsum saturation once silently
    dropped sub-ulp-probability tokens there), so its stream still
    equals the filter-free solo generate()."""
    lm, variables = lm_setup
    p1 = np.asarray([7, 3, 1], np.int32)
    p2 = np.asarray([2, 8], np.int32)
    bat = ContinuousBatcher(lm, variables, slots=2)
    r1 = bat.submit(p1, 6, temperature=1.4, rng=jax.random.PRNGKey(33))
    r2 = bat.submit(p2, 6, temperature=0.8, top_p=0.5,
                    rng=jax.random.PRNGKey(34))
    out = bat.run()
    np.testing.assert_array_equal(
        out[r1], _solo(lm, variables, p1, 6, temperature=1.4,
                       rng=jax.random.PRNGKey(33)))
    np.testing.assert_array_equal(
        out[r2], _solo(lm, variables, p2, 6, temperature=0.8, top_p=0.5,
                       rng=jax.random.PRNGKey(34)))


def test_per_request_top_p_matches_generate(lm_setup):
    """Mixed nucleus-p traffic in one batch matches each request's own
    generate(top_p=...) solo; a top_p=1.0 request rides the skip path."""
    lm, variables = lm_setup
    p1 = np.asarray([1, 5, 9], np.int32)
    p2 = np.asarray([2, 4], np.int32)
    bat = ContinuousBatcher(lm, variables, slots=2)
    r1 = bat.submit(p1, 5, temperature=0.9, top_p=0.6,
                    rng=jax.random.PRNGKey(31))
    r2 = bat.submit(p2, 5, temperature=1.2, top_p=1.0,
                    rng=jax.random.PRNGKey(32))
    out = bat.run()
    np.testing.assert_array_equal(
        out[r1], _solo(lm, variables, p1, 5, temperature=0.9, top_p=0.6,
                       rng=jax.random.PRNGKey(31)))
    np.testing.assert_array_equal(
        out[r2], _solo(lm, variables, p2, 5, temperature=1.2, top_p=1.0,
                       rng=jax.random.PRNGKey(32)))


def test_stats_and_metrics(lm_setup):
    """Serving observability: occupancy/queue stats and the global
    counters move as traffic flows."""
    from adapt_tpu.utils.metrics import global_metrics

    lm, variables = lm_setup
    global_metrics().reset()
    bat = ContinuousBatcher(lm, variables, slots=2, chunk=2)
    s = bat.stats()
    assert s["slots"] == 2 and s["active"] == 0 and s["queued"] == 0
    for i in range(3):
        bat.submit(np.asarray([1 + i, 2, 3], np.int32), 4)
    assert bat.stats()["queued"] == 3
    bat.tick()
    mid = bat.stats()
    assert mid["active"] >= 1 and mid["admitted"] >= 2
    bat.run()
    end = bat.stats()
    assert end["active"] == 0 and end["completed"] == 3
    assert end["ticks"] >= 1 and end["finished_unclaimed"] == 0


def test_stats_are_instance_scoped(lm_setup):
    """Two batchers in one process must not report each other's traffic
    (stats() reads instance counters, not the process registry)."""
    lm, variables = lm_setup
    a = ContinuousBatcher(lm, variables, slots=2)
    a.submit(np.asarray([1, 2], np.int32), 3)
    a.run()
    b = ContinuousBatcher(lm, variables, slots=2)
    sb = b.stats()
    assert sb["admitted"] == 0 and sb["completed"] == 0 and sb["ticks"] == 0
    sa = a.stats()
    assert sa["admitted"] == 1 and sa["completed"] == 1


def test_threaded_serving_matches_generate(lm_setup):
    """start()/result(): submit from the caller thread while the server
    thread ticks; every stream still equals its solo generate()."""
    lm, variables = lm_setup
    rng = np.random.RandomState(9)
    with ContinuousBatcher(lm, variables, slots=2, chunk=4) as bat:
        reqs = []
        for i in range(5):
            p = rng.randint(0, 37, size=rng.randint(2, 8)).astype(np.int32)
            kw = (
                dict(temperature=0.9, top_k=7,
                     rng=jax.random.PRNGKey(60 + i))
                if i % 2
                else {}
            )
            reqs.append((bat.submit(p, 4 + i, **kw), p, 4 + i, kw))
        for rid, p, steps, kw in reqs:
            got = bat.result(rid, timeout=120.0)
            np.testing.assert_array_equal(
                got, _solo(lm, variables, p, steps, **kw)
            )
    # stopped: a late result() raises rather than hanging
    with pytest.raises((RuntimeError, TimeoutError)):
        bat.result(10_000, timeout=0.2)


def test_gqa_requests_match_generate():
    """A GQA model serves through the batcher: slot caches allocate the
    smaller kv_heads layout and every stream still matches its solo
    generate()."""
    from adapt_tpu.models.transformer_lm import transformer_lm

    vocab = 31
    lm = transformer_lm(vocab=vocab, dim=32, depth=2, heads=4, mlp_dim=48,
                        max_len=48, kv_heads=2)
    variables = lm.graph.init(
        jax.random.PRNGKey(50), jnp.zeros((1, 4), jnp.int32)
    )
    rng = np.random.RandomState(51)
    prompts = [rng.randint(0, vocab, size=n).astype(np.int32)
               for n in (3, 7, 5)]
    steps = [6, 4, 5]

    bat = ContinuousBatcher(lm, variables, slots=2, chunk=1)
    # 2 kv heads, head_dim 8, max_len+1 cache rows.
    assert bat._caches[0][0].shape == (2, 2, 49, 8)
    ids = {bat.submit(p, s): i
           for i, (p, s) in enumerate(zip(prompts, steps))}
    out = bat.run()
    for rid, i in ids.items():
        want = _solo(lm, variables, prompts[i], steps[i])
        np.testing.assert_array_equal(out[rid], want, err_msg=f"req {i}")


def test_stop_sequences_truncate_at_first_match(lm_setup):
    """A stop sequence ends the stream at its first occurrence
    (inclusive); the emitted prefix equals solo generate()'s prefix."""
    lm, variables = lm_setup
    p = np.asarray([1, 2, 3], np.int32)
    full = _solo(lm, variables, p, 12)
    # Pick the stop sequence FROM the greedy stream so it must trigger.
    stop_seq = [int(full[4]), int(full[5])]
    bat = ContinuousBatcher(lm, variables, slots=2)
    rid = bat.submit(p, 12, stop=[stop_seq, [999]])
    out = bat.run()
    got = out[rid]
    assert list(got[-2:]) == stop_seq
    np.testing.assert_array_equal(got, full[: len(got)])
    assert len(got) <= 6  # ended at (or before) the planted match
    # A stop sequence that CANNOT occur (ids are always < vocab)
    # changes nothing — asserted unconditionally.
    rid2 = bat.submit(p, 12, stop=[[lm.vocab]])
    out2 = bat.run()
    np.testing.assert_array_equal(out2[rid2], full)


def test_cancel_queued_and_midflight(lm_setup):
    lm, variables = lm_setup
    p1 = np.asarray([4, 5, 6, 7], np.int32)
    p2 = np.asarray([8, 9], np.int32)
    bat = ContinuousBatcher(lm, variables, slots=1, chunk=2)
    r1 = bat.submit(p1, 30)
    r2 = bat.submit(p2, 5)  # waits in queue (1 slot)
    bat.tick()
    assert bat.cancel(r2)  # still queued -> dropped, empty result
    bat.tick()
    assert bat.cancel(r1)  # mid-flight -> partial stream
    assert not bat.cancel(12345)  # unknown id
    out = bat.run()
    assert out[r2].shape == (0,)
    partial = out[r1]
    assert 0 < len(partial) < 30
    np.testing.assert_array_equal(
        partial, _solo(lm, variables, p1, 30)[: len(partial)]
    )
    assert bat.stats()["active"] == 0
    assert not bat._cancelled  # no leaked cancel markers


def test_cancel_finished_request_returns_false(lm_setup):
    lm, variables = lm_setup
    p = np.asarray([1, 2], np.int32)
    bat = ContinuousBatcher(lm, variables, slots=1)
    rid = bat.submit(p, 3)
    out = bat.run()
    assert len(out[rid]) == 3
    assert not bat.cancel(rid)


def test_on_token_streams_every_committed_token(lm_setup):
    """The streaming callback sees exactly the final stream, in order,
    with correct indices — including the EOS token and across requests
    interleaved in one batcher."""
    lm, variables = lm_setup
    p1 = np.asarray([1, 2, 3], np.int32)
    p2 = np.asarray([4, 5], np.int32)
    streamed = {1: [], 2: []}

    def cb(tag):
        def on_token(rid, tok, idx):
            assert idx == len(streamed[tag])
            streamed[tag].append(tok)
        return on_token

    bat = ContinuousBatcher(lm, variables, slots=2, chunk=2)
    full1 = _solo(lm, variables, p1, 8)
    r1 = bat.submit(p1, 8, on_token=cb(1))
    r2 = bat.submit(p2, 6, eos_id=int(_solo(lm, variables, p2, 6)[3]),
                    on_token=cb(2))
    out = bat.run()
    np.testing.assert_array_equal(np.asarray(streamed[1]), out[r1])
    np.testing.assert_array_equal(np.asarray(streamed[2]), out[r2])
    np.testing.assert_array_equal(out[r1], full1)
    assert streamed[2][-1] == out[r2][-1]  # EOS streamed too


def test_on_token_exception_surfaces_to_result_waiters(lm_setup):
    """A raising callback in threaded mode must not strand result()
    waiters in a timeout: the server stops and result() re-raises."""
    lm, variables = lm_setup

    def bad(rid, tok, idx):
        raise RuntimeError("boom-in-callback")

    bat = ContinuousBatcher(lm, variables, slots=1)
    bat.start()
    try:
        rid = bat.submit(np.asarray([1, 2], np.int32), 4, on_token=bad)
        with pytest.raises(RuntimeError) as ei:
            bat.result(rid, timeout=60.0)
        assert "boom-in-callback" in repr(ei.value.__cause__)
    finally:
        bat._stopping = True  # thread already dead; stop() would join it
        with bat._cv:
            bat._cv.notify_all()
        bat._server = None


def test_batcher_logprobs_match_generate(lm_setup):
    """Served logprobs equal generate(return_logprobs=True)'s for the
    same request — greedy and sampled, including the prefill-sampled
    first token."""
    lm, variables = lm_setup
    p1 = np.asarray([1, 2, 3], np.int32)
    p2 = np.asarray([4, 5, 6, 7], np.int32)
    bat = ContinuousBatcher(lm, variables, slots=2)
    r1 = bat.submit(p1, 6)
    r2 = bat.submit(p2, 5, temperature=0.9, top_k=5,
                    rng=jax.random.PRNGKey(7))
    out = bat.run()
    for rid, p, steps, kw in (
        (r1, p1, 6, {}),
        (r2, p2, 5, dict(temperature=0.9, top_k=5,
                         rng=jax.random.PRNGKey(7))),
    ):
        want_t, want_lp = generate(
            lm, variables, jnp.asarray(p)[None], steps,
            return_logprobs=True, **kw,
        )
        np.testing.assert_array_equal(out[rid], np.asarray(want_t)[0])
        np.testing.assert_allclose(
            bat.logprobs(rid), np.asarray(want_lp)[0],
            rtol=2e-4, atol=2e-4,
        )
    with pytest.raises(KeyError):
        bat.logprobs(r1)  # already claimed


def test_fused_staging_transfer_counts(lm_setup):
    """The device-resident hot-path contract, asserted via the batcher's
    transfer-counting shim (every host->device staging call funnels
    through ``_h2d``, surfaced as ``stats()["h2d_transfers"]``):

    - a STEADY-STATE decode tick stages ZERO host arrays (the old path
      staged 7 per tick — tokens/pos/keys/temps/top_ks/top_ps/greedy);
    - an admission stages O(1) fused vectors (prompt ids + one int
      vector + one float vector + key block + insert index + the
      device-row setter's three), NOT one transfer per sampling field;
    - a retirement is one O(1) row-clear dispatch.
    """
    lm, variables = lm_setup
    bat = ContinuousBatcher(lm, variables, slots=2, chunk=2, top_k=5)
    p = np.asarray([1, 2, 3], np.int32)

    before = bat.stats()["h2d_transfers"]
    # Max out the per-request sampling surface: temperature + top_k +
    # top_p + rng schedule. O(fields) staging would pay per field.
    r1 = bat.submit(p, 40, temperature=0.9, top_p=0.9,
                    rng=jax.random.PRNGKey(1))
    bat.tick()
    per_admission = bat.stats()["h2d_transfers"] - before
    assert per_admission <= 10, per_admission

    before = bat.stats()["h2d_transfers"]
    for _ in range(4):
        bat.tick()  # request still decoding: pure steady state
    assert bat.stats()["h2d_transfers"] == before

    # Greedy second request (fewest sampling fields) costs the same
    # fused admission — the O(1)-not-O(fields) claim. Long enough not
    # to retire inside the measured tick (retiring is a +1 row-clear).
    before = bat.stats()["h2d_transfers"]
    r2 = bat.submit(p, 20)
    bat.tick()
    greedy_admission = bat.stats()["h2d_transfers"] - before
    assert greedy_admission == per_admission, (
        greedy_admission, per_admission,
    )
    out = bat.run()
    assert set(out) == {r1, r2}
