"""Model zoo tests: shapes, partitionability at the BASELINE cut lists, and
stage-composition equivalence on small inputs (the SURVEY §4 oracle applied
to real model graphs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.graph import partition, valid_cut_points
from adapt_tpu.models.efficientnet import efficientnet_b0
from adapt_tpu.models.resnet import RESNET50_3STAGE_CUTS, resnet50
from adapt_tpu.models.vit import vit_block_cuts, vit_tiny


@pytest.fixture(scope="module")
def small_image():
    # 64x64 keeps CPU-test conv time low; graphs are resolution-agnostic.
    return jnp.ones((1, 64, 64, 3), jnp.float32)


def test_resnet50_graph_structure():
    g = resnet50()
    # 16 blocks -> 16 merge nodes; merges + stem are the valid cuts.
    cuts = valid_cut_points(g)
    assert "stem" in cuts
    assert "conv3_block1_out" in cuts
    assert "conv3_block1_branch" not in cuts
    merges = [n for n in g.topo_order() if n.endswith("_out")]
    assert len(merges) == 16


def test_resnet50_partition_and_compose(small_image):
    g = resnet50(num_classes=10)
    variables = g.init(jax.random.PRNGKey(0), small_image)
    y_full = g.apply(variables, small_image)
    assert y_full.shape == (1, 10)
    plan = partition(g, list(RESNET50_3STAGE_CUTS))
    assert plan.num_stages == 3
    sv = plan.extract_variables(variables)
    y = plan.compose(sv, small_image)
    np.testing.assert_array_equal(np.asarray(y_full), np.asarray(y))


def test_resnet152_cuts_exist():
    from adapt_tpu.models.resnet import RESNET152_8STAGE_CUTS, resnet152

    g = resnet152(num_classes=10)
    plan = partition(g, list(RESNET152_8STAGE_CUTS))
    assert plan.num_stages == 8


def test_vit_tiny_partition_and_compose():
    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    y_full = g.apply(variables, x)
    assert y_full.shape == (2, 10)
    cuts = vit_block_cuts(4, 2)
    assert cuts == ["encoder_block_1"]
    plan = partition(g, cuts)
    sv = plan.extract_variables(variables)
    np.testing.assert_array_equal(
        np.asarray(y_full), np.asarray(plan.compose(sv, x))
    )


def test_efficientnet_b0_dag_partition(small_image):
    g = efficientnet_b0(num_classes=10)
    variables = g.init(jax.random.PRNGKey(1), small_image)
    y_full = g.apply(variables, small_image)
    assert y_full.shape == (1, 10)
    # Multi-branch DAG: identity-residual blocks create joins; partition at
    # a couple of add-merge points.
    cuts = [c for c in valid_cut_points(g) if c.endswith("_add")]
    assert len(cuts) >= 4  # several residual merges exist
    plan = partition(g, cuts[:2])
    sv = plan.extract_variables(variables)
    np.testing.assert_array_equal(
        np.asarray(y_full), np.asarray(plan.compose(sv, small_image))
    )


def test_bfloat16_resnet(small_image):
    g = resnet50(num_classes=10, dtype=jnp.bfloat16)
    variables = g.init(jax.random.PRNGKey(0), small_image)
    y = g.apply(variables, small_image)
    assert y.dtype == jnp.float32  # head casts logits back to f32
    assert np.isfinite(np.asarray(y)).all()


def test_vit_block_cuts_validation():
    from adapt_tpu.models.vit import vit_block_cuts

    with pytest.raises(ValueError, match="cannot split"):
        vit_block_cuts(4, 8)
    assert vit_block_cuts(4, 4) == [
        "encoder_block_0",
        "encoder_block_1",
        "encoder_block_2",
    ]
    assert vit_block_cuts(12, 3) == ["encoder_block_3", "encoder_block_7"]


def test_vit_attention_flash_matches_oracle(rng):
    """The product-path attention (MultiHeadSelfAttention on the Pallas
    flash kernel) must match the same module running the jnp oracle with
    identical params — the flax-parity check for the kernel wiring."""
    import numpy as np

    from adapt_tpu.models.vit import MultiHeadSelfAttention
    from adapt_tpu.ops.attention import attention_reference

    x = jax.random.normal(rng, (2, 65, 64))
    # Pin the Pallas path: the measured dispatch would route this small
    # shape to the XLA oracle, making the comparison vacuous.
    m_flash = MultiHeadSelfAttention(heads=4, attn_prefer="pallas")
    m_ref = MultiHeadSelfAttention(heads=4, attn_fn=attention_reference)
    variables = m_flash.init(jax.random.PRNGKey(7), x)
    y_flash = m_flash.apply(variables, x)
    y_ref = m_ref.apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(y_flash), np.asarray(y_ref), rtol=1e-2, atol=1e-2
    )
    # And gradients flow through the kernel (custom VJP): training-path
    # usability, not just inference.
    g = jax.grad(lambda v: jnp.sum(m_flash.apply(v, x) ** 2))(variables)
    assert all(
        bool(jnp.all(jnp.isfinite(leaf))) for leaf in jax.tree.leaves(g)
    )


def test_resnet_space_to_depth_stem(rng):
    """The MXU-friendly s2d stem must produce the same output shape as the
    7x7/s2 stem, keep every cut name valid, and reject odd inputs."""
    from adapt_tpu.models.resnet import RESNET50_3STAGE_CUTS, resnet50

    g = resnet50(num_classes=10, stem="s2d")
    x = jnp.ones((1, 64, 64, 3))
    v = jax.jit(g.init)(rng, x)
    y = jax.jit(g.apply)(v, x)
    assert y.shape == (1, 10)
    # Cut names unchanged: the baseline 3-stage plan still partitions.
    plan = partition(g, list(RESNET50_3STAGE_CUTS))
    assert plan.num_stages == 3
    with pytest.raises(ValueError, match="unknown stem"):
        resnet50(stem="bogus")
    with pytest.raises(ValueError, match="even"):
        g.apply(v, jnp.ones((1, 63, 63, 3)))


# -- transformer LM ---------------------------------------------------------


def test_lm_cached_decode_matches_full_forward():
    """Teacher-forced incremental decoding (prefill + per-token cached
    steps) must reproduce the full causal forward's logits position for
    position — the KV cache is a schedule change, not a model change."""
    from adapt_tpu.models.transformer_lm import lm_tiny, logits_full

    lm = lm_tiny(vocab=97, max_len=32)
    ids = jax.random.randint(jax.random.PRNGKey(0), (2, 12), 0, 97)
    variables = lm.graph.init(jax.random.PRNGKey(1), ids)
    full = np.asarray(logits_full(lm, variables, ids))  # (2, 12, 97)

    g = lm.graph
    embed = g.node("embed").module
    head = g.node("head").module
    blocks = [g.node(n).module for n in lm.block_names]

    # Prefill on the first 5 tokens, then feed ground-truth tokens 5..11
    # through decode_step; logits must match the full forward at every
    # position.
    s0 = 5
    h = embed.apply(variables["embed"], ids[:, :s0])
    caches = []
    for name, block in zip(lm.block_names, blocks):
        h, ck, cv = block.apply(
            variables[name], h, lm.max_len, method="prefill"
        )
        caches.append([ck, cv])
    prefill_logits = np.asarray(head.apply(variables["head"], h))
    np.testing.assert_allclose(
        prefill_logits, full[:, :s0], rtol=2e-4, atol=2e-4
    )

    for t in range(s0, ids.shape[1]):
        x_t = embed.apply(
            variables["embed"], ids[:, t : t + 1], t, method="embed_at"
        )
        for i, (name, block) in enumerate(zip(lm.block_names, blocks)):
            x_t, ck, cv = block.apply(
                variables[name], x_t, *caches[i], t, method="decode_step"
            )
            caches[i] = [ck, cv]
        step_logits = np.asarray(head.apply(variables["head"], x_t))[:, 0]
        np.testing.assert_allclose(
            step_logits, full[:, t], rtol=2e-4, atol=2e-4,
            err_msg=f"position {t}",
        )


def test_lm_generate_matches_uncached_greedy():
    """generate() (compiled prefill + scan decode) must emit exactly the
    tokens an uncached greedy loop over the full forward would."""
    from adapt_tpu.models.transformer_lm import generate, lm_tiny, logits_full

    lm = lm_tiny(vocab=61, max_len=24)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, 61)
    variables = lm.graph.init(jax.random.PRNGKey(3), prompt)
    steps = 6

    out = np.asarray(generate(lm, variables, prompt, steps))

    ids = prompt
    expect = []
    for _ in range(steps):
        nxt = jnp.argmax(logits_full(lm, variables, ids)[:, -1], axis=-1)
        expect.append(np.asarray(nxt))
        ids = jnp.concatenate([ids, nxt[:, None].astype(ids.dtype)], axis=1)
    expect = np.stack(expect, axis=1)
    np.testing.assert_array_equal(out, expect)


def test_lm_pipeline_partition_parity():
    """The LM graph cuts at decoder blocks like ViT: composed stages ==
    full model."""
    from adapt_tpu.graph.partition import partition
    from adapt_tpu.models.transformer_lm import lm_tiny, logits_full

    lm = lm_tiny(vocab=41, max_len=16)
    ids = jax.random.randint(jax.random.PRNGKey(4), (2, 10), 0, 41)
    variables = lm.graph.init(jax.random.PRNGKey(5), ids)
    full = np.asarray(logits_full(lm, variables, ids))

    plan = partition(lm.graph, ["decoder_block_1", "decoder_block_3"])
    svars = plan.extract_variables(variables)
    composed = np.asarray(plan.compose(svars, ids))
    np.testing.assert_allclose(composed, full, rtol=2e-4, atol=2e-4)


def test_lm_generate_rejects_overflow():
    from adapt_tpu.models.transformer_lm import generate, lm_tiny

    lm = lm_tiny(vocab=17, max_len=8)
    prompt = jnp.zeros((1, 6), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        generate(lm, prompt=prompt, variables={}, steps=4)


def test_lm_serves_through_pipeline(devices):
    """The LM graph family works with the serving machinery end-to-end:
    partitioned at block cuts, pipelined over devices via LocalPipeline,
    streaming token batches — same contract as the CNN families."""
    from adapt_tpu.graph.partition import partition
    from adapt_tpu.models.transformer_lm import lm_tiny, logits_full
    from adapt_tpu.runtime.pipeline import LocalPipeline

    lm = lm_tiny(vocab=53, max_len=16)
    ids = [
        jax.random.randint(jax.random.PRNGKey(i), (2, 9), 0, 53)
        for i in range(4)
    ]
    variables = lm.graph.init(jax.random.PRNGKey(99), ids[0])
    plan = partition(lm.graph, ["decoder_block_1", "decoder_block_3"])
    pipe = LocalPipeline(
        plan, variables, devices=devices[: plan.num_stages]
    )
    outs = pipe.stream(ids)
    for x, y in zip(ids, outs):
        np.testing.assert_allclose(
            np.asarray(y),
            np.asarray(logits_full(lm, variables, x)),
            rtol=2e-4,
            atol=2e-4,
        )


def test_lm_generate_rejects_zero_steps():
    from adapt_tpu.models.transformer_lm import generate, lm_tiny

    lm = lm_tiny(vocab=17, max_len=8)
    with pytest.raises(ValueError, match="steps"):
        generate(lm, {}, jnp.zeros((1, 2), jnp.int32), 0)


def test_lm_generate_sampling_and_eos():
    """Serving knobs: top_k=1 sampling degenerates to greedy whatever the
    temperature; eos_id pads a finished row with EOS forever after."""
    from adapt_tpu.models.transformer_lm import generate, lm_tiny

    lm = lm_tiny(vocab=31, max_len=24)
    prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 4), 0, 31)
    variables = lm.graph.init(jax.random.PRNGKey(7), prompt)

    greedy = np.asarray(generate(lm, variables, prompt, 8))
    topk1 = np.asarray(
        generate(
            lm, variables, prompt, 8,
            temperature=1.7, top_k=1, rng=jax.random.PRNGKey(8),
        )
    )
    np.testing.assert_array_equal(greedy, topk1)

    # Same key -> same sample; different key -> (here) a different draw.
    s1 = np.asarray(
        generate(lm, variables, prompt, 8, temperature=1.0,
                 rng=jax.random.PRNGKey(9))
    )
    s2 = np.asarray(
        generate(lm, variables, prompt, 8, temperature=1.0,
                 rng=jax.random.PRNGKey(9))
    )
    np.testing.assert_array_equal(s1, s2)

    # EOS: declare the greedy path's first emission to be EOS — every
    # subsequent token on that row must be EOS too.
    eos = int(greedy[0, 0])
    out = np.asarray(generate(lm, variables, prompt, 8, eos_id=eos))
    assert (out[0] == eos).all()

    with pytest.raises(ValueError, match="rng"):
        generate(lm, variables, prompt, 4, temperature=0.5)


def test_lm_generate_ragged_prompts_match_per_row():
    """Batched ragged generation (right-padded prompts + prompt_lengths)
    must emit, per row, exactly what generating that row alone emits —
    left-alignment, per-row position ids, and padding masks are internal
    bookkeeping, never visible in the output."""
    from adapt_tpu.models.transformer_lm import generate, lm_tiny

    lm = lm_tiny(vocab=43, max_len=24)
    lens = [3, 7, 5]
    s0 = max(lens)
    rows = [
        jax.random.randint(jax.random.PRNGKey(10 + i), (1, n), 0, 43)
        for i, n in enumerate(lens)
    ]
    variables = lm.graph.init(jax.random.PRNGKey(20), rows[1])

    batched = jnp.zeros((len(lens), s0), jnp.int32)
    for i, r in enumerate(rows):
        batched = batched.at[i, : lens[i]].set(r[0])
    out = np.asarray(
        generate(
            lm, variables, batched, 6,
            prompt_lengths=jnp.asarray(lens),
        )
    )
    for i, r in enumerate(rows):
        solo = np.asarray(generate(lm, variables, r, 6))
        np.testing.assert_array_equal(out[i], solo[0], err_msg=f"row {i}")


def test_lm_generate_rejects_bad_prompt_lengths():
    from adapt_tpu.models.transformer_lm import generate, lm_tiny

    lm = lm_tiny(vocab=11, max_len=16)
    prompt = jnp.zeros((2, 4), jnp.int32)
    with pytest.raises(ValueError, match="prompt_lengths"):
        generate(lm, {}, prompt, 2, prompt_lengths=jnp.asarray([2, 6]))
    with pytest.raises(ValueError, match="prompt_lengths"):
        generate(lm, {}, prompt, 2, prompt_lengths=jnp.asarray([0, 3]))
    with pytest.raises(ValueError, match="shape"):
        generate(lm, {}, prompt, 2, prompt_lengths=jnp.asarray([3]))


def test_lm_generate_int8_kv_cache():
    """kv_cache_dtype='int8' stores (int8 values, f32 scales) caches.
    Teacher-forced logits through the quantized cache must track the
    native-cache logits closely (absmax-per-vector int8, ~0.4% scale
    granularity), and greedy generation runs end to end."""
    from adapt_tpu.models.transformer_lm import generate, lm_tiny

    lm = lm_tiny(vocab=37, max_len=24)
    prompt = jax.random.randint(jax.random.PRNGKey(30), (2, 6), 0, 37)
    variables = lm.graph.init(jax.random.PRNGKey(31), prompt)

    g = lm.graph
    embed = g.node("embed").module
    head = g.node("head").module
    blocks = [g.node(n).module for n in lm.block_names]

    # One FIXED token sequence feeds both runs (true teacher forcing):
    # a quantization-induced argmax flip must not send the two runs down
    # different decode paths, or the logits comparison is meaningless.
    forced = jax.random.randint(jax.random.PRNGKey(32), (4, 2), 0, 37)

    def run(quant):
        h = embed.apply(variables["embed"], prompt)
        caches = []
        for name, block in zip(lm.block_names, blocks):
            h, ck, cv = block.apply(
                variables[name], h, lm.max_len, None, quant,
                method="prefill",
            )
            caches.append([ck, cv])
        logits = [np.asarray(head.apply(variables["head"], h[:, -1:]))]
        for step, t in enumerate(range(6, 10)):
            x_t = embed.apply(
                variables["embed"], forced[step][:, None], t,
                method="embed_at",
            )
            for i, (name, block) in enumerate(zip(lm.block_names, blocks)):
                x_t, ck, cv = block.apply(
                    variables[name], x_t, *caches[i], t, None, quant,
                    method="decode_step",
                )
                caches[i] = [ck, cv]
            logits.append(np.asarray(head.apply(variables["head"], x_t)))
        return np.concatenate(logits, axis=1), caches

    lg_native, _ = run(False)
    lg_int8, caches = run(True)
    assert caches[0][0][0].dtype == jnp.int8
    assert caches[0][0][1].dtype == jnp.float32
    scale = np.abs(lg_native).max()
    np.testing.assert_allclose(
        lg_int8 / scale, lg_native / scale, atol=0.05
    )

    out = np.asarray(
        generate(lm, variables, prompt, 6, kv_cache_dtype="int8")
    )
    assert out.shape == (2, 6) and (out >= 0).all() and (out < 37).all()

    with pytest.raises(ValueError, match="kv_cache_dtype"):
        generate(lm, variables, prompt, 2, kv_cache_dtype="fp8")


def test_lm_generate_top_p():
    """Nucleus sampling: top_p=1.0 filters nothing (stream identical to
    the unfiltered sampler), top_p→0 degenerates to greedy (only the
    top-1 token survives the nucleus), and mid-range p is deterministic
    per key."""
    from adapt_tpu.models.transformer_lm import generate, lm_tiny

    lm = lm_tiny(vocab=29, max_len=24)
    prompt = jax.random.randint(jax.random.PRNGKey(15), (2, 4), 0, 29)
    variables = lm.graph.init(jax.random.PRNGKey(16), prompt)

    base = np.asarray(
        generate(lm, variables, prompt, 8, temperature=1.0,
                 rng=jax.random.PRNGKey(17))
    )
    all_mass = np.asarray(
        generate(lm, variables, prompt, 8, temperature=1.0, top_p=1.0,
                 rng=jax.random.PRNGKey(17))
    )
    np.testing.assert_array_equal(base, all_mass)

    greedy = np.asarray(generate(lm, variables, prompt, 8))
    tiny_p = np.asarray(
        generate(lm, variables, prompt, 8, temperature=1.7, top_p=1e-6,
                 rng=jax.random.PRNGKey(18))
    )
    np.testing.assert_array_equal(greedy, tiny_p)

    s1 = np.asarray(generate(lm, variables, prompt, 8, temperature=1.0,
                             top_p=0.7, rng=jax.random.PRNGKey(19)))
    s2 = np.asarray(generate(lm, variables, prompt, 8, temperature=1.0,
                             top_p=0.7, rng=jax.random.PRNGKey(19)))
    np.testing.assert_array_equal(s1, s2)

    with pytest.raises(ValueError, match="top_p"):
        generate(lm, variables, prompt, 2, temperature=1.0, top_p=1.5,
                 rng=jax.random.PRNGKey(20))


# -- grouped-query attention (GQA / MQA) ------------------------------------


def _gqa_lm(vocab=47, heads=4, kv_heads=2, max_len=24):
    from adapt_tpu.models.transformer_lm import transformer_lm

    return transformer_lm(
        vocab=vocab, dim=32, depth=2, heads=heads, mlp_dim=48,
        max_len=max_len, kv_heads=kv_heads,
    )


@pytest.mark.parametrize("kv_heads", [1, 2])
def test_lm_gqa_cached_decode_matches_full_forward(kv_heads):
    """GQA's cached decode (grouped q rows over the small kv_heads cache)
    must reproduce the full-forward logits position for position, exactly
    like MHA — the cache layout is a schedule change, not a model change.
    Also pins the capacity claim: the cache's head axis is kv_heads."""
    from adapt_tpu.models.transformer_lm import logits_full

    vocab = 47
    lm = _gqa_lm(vocab=vocab, heads=4, kv_heads=kv_heads)
    ids = jax.random.randint(jax.random.PRNGKey(40), (2, 10), 0, vocab)
    variables = lm.graph.init(jax.random.PRNGKey(41), ids)
    full = np.asarray(logits_full(lm, variables, ids))

    g = lm.graph
    embed = g.node("embed").module
    head = g.node("head").module
    blocks = [g.node(n).module for n in lm.block_names]

    s0 = 4
    h = embed.apply(variables["embed"], ids[:, :s0])
    caches = []
    for name, block in zip(lm.block_names, blocks):
        h, ck, cv = block.apply(
            variables[name], h, lm.max_len, method="prefill"
        )
        # The whole point of GQA: the cache head axis is kv_heads, not
        # heads — 4/kv_heads x less HBM per decoded context.
        assert ck.shape == (2, kv_heads, lm.max_len, 32 // 4)
        caches.append([ck, cv])
    prefill_logits = np.asarray(head.apply(variables["head"], h))
    np.testing.assert_allclose(
        prefill_logits, full[:, :s0], rtol=2e-4, atol=2e-4
    )

    for t in range(s0, ids.shape[1]):
        x_t = embed.apply(
            variables["embed"], ids[:, t : t + 1], t, method="embed_at"
        )
        for i, (name, block) in enumerate(zip(lm.block_names, blocks)):
            x_t, ck, cv = block.apply(
                variables[name], x_t, *caches[i], t, method="decode_step"
            )
            caches[i] = [ck, cv]
        step_logits = np.asarray(head.apply(variables["head"], x_t))[:, 0]
        np.testing.assert_allclose(
            step_logits, full[:, t], rtol=2e-4, atol=2e-4,
            err_msg=f"kv_heads={kv_heads} position {t}",
        )


def test_lm_gqa_generate_matches_uncached_greedy():
    """generate() on a GQA model == uncached greedy loop, token for
    token (same contract the MHA test pins)."""
    from adapt_tpu.models.transformer_lm import generate, logits_full

    vocab = 43
    lm = _gqa_lm(vocab=vocab, heads=4, kv_heads=2)
    prompt = jax.random.randint(jax.random.PRNGKey(42), (2, 5), 0, vocab)
    variables = lm.graph.init(jax.random.PRNGKey(43), prompt)
    steps = 6

    out = np.asarray(generate(lm, variables, prompt, steps))

    ids = prompt
    expect = []
    for _ in range(steps):
        nxt = jnp.argmax(logits_full(lm, variables, ids)[:, -1], axis=-1)
        expect.append(np.asarray(nxt))
        ids = jnp.concatenate([ids, nxt[:, None].astype(ids.dtype)], axis=1)
    np.testing.assert_array_equal(out, np.stack(expect, axis=1))


def test_lm_gqa_int8_cache_composes():
    """GQA x int8: the quantized cache keeps the kv_heads layout (the
    two capacity knobs multiply) and generation runs end to end."""
    from adapt_tpu.models.transformer_lm import generate

    vocab = 41
    lm = _gqa_lm(vocab=vocab, heads=4, kv_heads=1, max_len=24)  # MQA
    prompt = jax.random.randint(jax.random.PRNGKey(44), (2, 6), 0, vocab)
    variables = lm.graph.init(jax.random.PRNGKey(45), prompt)

    g = lm.graph
    embed = g.node("embed").module
    block = g.node(lm.block_names[0]).module
    h = embed.apply(variables["embed"], prompt)
    _, (kv, ks), _ = block.apply(
        variables[lm.block_names[0]], h, lm.max_len, None, True,
        method="prefill",
    )
    assert kv.dtype == jnp.int8 and kv.shape == (2, 1, lm.max_len, 8)
    assert ks.shape == (2, 1, lm.max_len, 1)

    out = np.asarray(
        generate(lm, variables, prompt, 6, kv_cache_dtype="int8")
    )
    native = np.asarray(generate(lm, variables, prompt, 6))
    assert out.shape == (2, 6) and (out >= 0).all() and (out < vocab).all()
    # int8 rounding may legitimately flip an argmax, so token equality
    # is not the contract here — the int8 logits-tracking contract is
    # pinned by test_lm_generate_int8_kv_cache.
    assert native.shape == out.shape


def test_lm_gqa_validation():
    """kv_heads must divide heads and sit in [1, heads]; kv_heads ==
    heads (or None) keeps the fused-QKV MHA parameter structure."""
    from adapt_tpu.models.transformer_lm import transformer_lm

    with pytest.raises(ValueError, match="kv_heads"):
        lm = _gqa_lm(heads=4, kv_heads=3)
        lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
    with pytest.raises(ValueError, match="kv_heads"):
        lm = _gqa_lm(heads=4, kv_heads=8)
        lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )

    mha = transformer_lm(vocab=11, dim=16, depth=1, heads=4, mlp_dim=16,
                         max_len=8)
    explicit = transformer_lm(vocab=11, dim=16, depth=1, heads=4,
                              mlp_dim=16, max_len=8, kv_heads=4)
    ids = jnp.zeros((1, 4), jnp.int32)
    v1 = mha.graph.init(jax.random.PRNGKey(7), ids)
    v2 = explicit.graph.init(jax.random.PRNGKey(7), ids)
    assert jax.tree.structure(v1) == jax.tree.structure(v2)


def test_generate_logprobs_match_full_forward():
    """return_logprobs: the reported score of each emitted token equals
    log_softmax of the full causal forward's logits at that position —
    and tokens are unchanged vs the plain call."""
    from adapt_tpu.models.transformer_lm import (
        generate, lm_tiny, logits_full,
    )

    lm = lm_tiny(vocab=37, max_len=32)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, 37)
    plain = np.asarray(generate(lm, variables, prompt, 6))
    toks, lps = generate(lm, variables, prompt, 6, return_logprobs=True)
    toks, lps = np.asarray(toks), np.asarray(lps)
    np.testing.assert_array_equal(toks, plain)
    assert lps.shape == (2, 6) and (lps <= 0).all()
    ids = np.concatenate([np.asarray(prompt), toks], axis=1)
    for t in range(6):
        lg = logits_full(lm, variables, jnp.asarray(ids[:, : 5 + t]))[:, -1]
        want = np.asarray(jax.nn.log_softmax(lg, axis=-1))
        got_tok = toks[:, t]
        np.testing.assert_allclose(
            lps[:, t], want[np.arange(2), got_tok], rtol=2e-4, atol=2e-4,
            err_msg=f"step {t}",
        )


def test_generate_logprobs_sampled_score_is_models_own():
    """Sampled generation with temperature/top-k still reports the RAW
    model log-softmax of the chosen token (not the tempered/filtered
    distribution)."""
    from adapt_tpu.models.transformer_lm import generate, lm_tiny

    lm = lm_tiny(vocab=37, max_len=32)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, 37)
    toks, lps = generate(
        lm, variables, prompt, 5, temperature=1.3, top_k=5,
        rng=jax.random.PRNGKey(3), return_logprobs=True,
    )
    plain = generate(
        lm, variables, prompt, 5, temperature=1.3, top_k=5,
        rng=jax.random.PRNGKey(3),
    )
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(plain))
    lps = np.asarray(lps)
    assert (lps <= 0).all() and np.isfinite(lps).all()
