"""Batched speculative decoding in the continuous batcher is LOSSLESS
per row: whatever the draft proposes and however acceptance staggers
across slots, every request's emitted stream must equal its solo
``generate()`` output token-for-token — across staggered admissions,
retirements, cancels, EOS/stop boundaries, and both KV layouts (dense
slot strips and paged pools). The fixed-shape contract rides along:
the spec tick compiles exactly TWO programs (draft scan, fused verify)
however rows desynchronize, and a steady-state spec tick stages zero
host arrays."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.config import SpeculativeConfig
from adapt_tpu.models.transformer_lm import (
    generate,
    lm_tiny,
    transformer_lm,
)
from adapt_tpu.runtime.continuous import ContinuousBatcher


@pytest.fixture(scope="module")
def lm_setup():
    # Deliberately SMALLER than lm_tiny (2 blocks, dim 32): every
    # batcher instance compiles its own verify/admission programs, and
    # losslessness is a scheduling property, not a model-size one —
    # tier-1 wall time is the budget here (ROADMAP.md).
    lm = transformer_lm(37, 32, 2, 2, 64, max_len=48, name="spec_target")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture(scope="module")
def draft_setup():
    # Same vocab, smaller independent model: a REAL draft whose
    # proposals are mostly wrong (adversarial acceptance).
    draft = transformer_lm(37, 16, 1, 1, 32, max_len=48, name="draft")
    variables = draft.graph.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32)
    )
    return draft, variables


def _solo(lm, variables, prompt, steps, **kw):
    return np.asarray(
        generate(lm, variables, jnp.asarray(prompt)[None], steps, **kw)
    )[0]


def test_spec_staggered_desync_matches_generate(lm_setup):
    """Perfect draft (the target itself), staggered arrivals, mixed
    lengths: every stream equals solo generate(), acceptance is 1.0,
    and the tick count proves multi-token commits (fewer verify passes
    than emitted tokens — the tokens-per-weight-stream win)."""
    lm, variables = lm_setup
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (3, 9, 5, 12, 7)]
    steps = [9, 14, 8, 3, 11]
    bat = ContinuousBatcher(
        lm, variables, slots=3, draft_lm=lm, draft_variables=variables,
        speculative=SpeculativeConfig(draft_k=3),
    )
    ids = {}
    for i in range(2):
        ids[bat.submit(prompts[i], steps[i])] = i
    bat.tick()
    bat.tick()
    for i in range(2, 5):  # arrive while the first two are mid-decode
        ids[bat.submit(prompts[i], steps[i])] = i
    out = bat.run()
    for rid, i in ids.items():
        np.testing.assert_array_equal(
            out[rid], _solo(lm, variables, prompts[i], steps[i]),
            err_msg=f"req {i}",
        )
    s = bat.stats()
    assert s["spec_acceptance"] == 1.0
    # A perfect draft commits draft_k + 1 = 4 tokens per slot-tick past
    # the prefill token; the plain tick commits chunk of them per
    # compiled pass. The whole 45-token workload must take well under
    # one tick per token.
    assert s["ticks"] < sum(steps)
    # Logprob carry-through: the spec tick's fused verify records the
    # same per-token scores generate(return_logprobs=True) reports.
    rid0 = next(r for r, i in ids.items() if i == 0)
    want_t, want_lp = generate(
        lm, variables, jnp.asarray(prompts[0])[None], steps[0],
        return_logprobs=True,
    )
    np.testing.assert_allclose(
        bat.logprobs(rid0), np.asarray(want_lp)[0], rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("draft_k", [1, 4])
def test_spec_adversarial_draft_lossless(lm_setup, draft_setup, draft_k):
    """An independent (mostly-rejected) draft changes ONLY the tick
    count — rows at acceptance 0 still advance one correction token per
    tick and match generate() exactly."""
    lm, variables = lm_setup
    draft, dvars = draft_setup
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (4, 7, 2)]
    bat = ContinuousBatcher(
        lm, variables, slots=2, draft_lm=draft, draft_variables=dvars,
        speculative=SpeculativeConfig(draft_k=draft_k),
    )
    ids = {bat.submit(p, 7): p for p in prompts}
    out = bat.run()
    for rid, p in ids.items():
        np.testing.assert_array_equal(
            out[rid], _solo(lm, variables, p, 7)
        )
    assert 0.0 <= bat.stats()["spec_acceptance"] <= 1.0


def test_spec_paged_with_prefix_sharing(lm_setup, draft_setup):
    """Speculation over the paged layout composes with prefix caching:
    requests sharing a prompt prefix (one admitted via shared pages)
    still match their solo streams, and pages free on retirement."""
    lm, variables = lm_setup
    draft, dvars = draft_setup
    shared = np.arange(1, 17, dtype=np.int32)  # two full 8-token pages
    p2 = np.concatenate([shared, [20, 21]]).astype(np.int32)
    bat = ContinuousBatcher(
        lm, variables, slots=2, kv_layout="paged", page_size=8,
        draft_lm=draft, draft_variables=dvars,
    )
    r1 = bat.submit(shared, 6)
    r2 = bat.submit(p2, 8)
    out = bat.run()
    np.testing.assert_array_equal(
        out[r1], _solo(lm, variables, shared, 6)
    )
    np.testing.assert_array_equal(out[r2], _solo(lm, variables, p2, 8))
    s = bat.stats()
    assert s["prefix_hits"] >= 1  # r2 rode r1's registered pages
    assert s["pages_in_use"] == 0  # slack pages came back too


def test_spec_eos_stop_cancel_at_acceptance_boundaries(lm_setup):
    """EOS inside an accepted block finishes the request there (the
    rest of the block is discarded garbage); stop sequences and cancels
    latch through the same commit path."""
    lm, variables = lm_setup
    p = np.asarray([4, 8, 15], np.int32)
    full = _solo(lm, variables, p, 10)
    eos = int(full[3])  # finishes after 4 tokens, mid-accepted-block
    bat = ContinuousBatcher(
        lm, variables, slots=2, draft_lm=lm, draft_variables=variables,
        speculative=SpeculativeConfig(draft_k=4),
    )
    r_eos = bat.submit(p, 10, eos_id=eos)
    stop_seq = [int(full[1]), int(full[2])]
    r_stop = bat.submit(p, 10, stop=[stop_seq])
    out = bat.run()
    n = len(out[r_eos])
    assert out[r_eos][-1] == eos and eos not in out[r_eos][:-1]
    np.testing.assert_array_equal(
        out[r_eos], _solo(lm, variables, p, 10, eos_id=eos)[:n]
    )
    assert list(out[r_stop][-2:]) == stop_seq
    np.testing.assert_array_equal(
        out[r_stop], full[: len(out[r_stop])]
    )
    # Cancel mid-flight: partial stream, slot freed, no leaked markers.
    r_long = bat.submit(np.asarray([1, 2], np.int32), 30)
    bat.tick()
    assert bat.cancel(r_long)
    out = bat.run()
    partial = out[r_long]
    assert 0 < len(partial) < 30
    np.testing.assert_array_equal(
        partial,
        _solo(lm, variables, np.asarray([1, 2], np.int32), 30)[
            : len(partial)
        ],
    )
    assert not bat._cancelled


def test_spec_tick_fixed_shape_zero_h2d_and_observability(
    lm_setup, draft_setup,
):
    """The TPU shape contract, counter-asserted: across a whole
    staggered workload the spec tick compiles exactly TWO programs (the
    draft scan and the fused verify) — per-slot acceptance history
    never forks a variant — and a steady-state spec tick stages ZERO
    host arrays (the PR-1 fused-staging contract carried through). The
    observability carry-through rides the same workload:
    continuous.spec_acceptance gauge + spec_accepted_per_tick histogram
    in the registry, decode.draft / decode.verify spans in the tracer
    tagged with the tick's request ids."""
    from adapt_tpu.utils.metrics import global_metrics
    from adapt_tpu.utils.profiling import global_compile_sentinel
    from adapt_tpu.utils.tracing import global_tracer

    lm, variables = lm_setup
    draft, dvars = draft_setup
    global_metrics().reset()
    tracer = global_tracer()
    was_enabled = tracer.enabled
    tracer.enabled = True
    try:
        # The two-program guard is expressed through the compile
        # sentinel's PUBLIC API (utils.profiling): constructing the
        # batcher registers both decode programs (and re-arms their
        # warmup); compiles() reads the watched jit cache sizes — no
        # raw _cache_size() poking.
        sentinel = global_compile_sentinel()
        bat = ContinuousBatcher(
            lm, variables, slots=2, draft_lm=draft, draft_variables=dvars,
        )
        assert {
            "continuous.spec_verify", "speculative.draft_chunk"
        } <= set(sentinel.watched())
        verify_before = sentinel.compiles("continuous.spec_verify")
        r1 = bat.submit(np.asarray([1, 2, 3], np.int32), 40)
        bat.tick()  # admission + first round compiles both programs
        # Exactly ONE verify variant for this batcher (self is the jit
        # key; draft_chunk may already be warm from an
        # identically-shaped earlier batcher — the draft scan is shared
        # across instances by design, its own fixed-shape evidence).
        assert (
            sentinel.compiles("continuous.spec_verify") - verify_before
            == 1
        )
        draft_entries = sentinel.compiles("speculative.draft_chunk")
        verify_entries = sentinel.compiles("continuous.spec_verify")
        before = bat.stats()["h2d_transfers"]
        for _ in range(4):
            bat.tick()  # pure steady state: desynchronized acceptance
        assert bat.stats()["h2d_transfers"] == before
        # Staggered churn: admissions, retirements, a second wave —
        # none of it may add a compiled variant to either decode
        # program.
        r2 = bat.submit(np.asarray([5, 6], np.int32), 3)
        out = {}
        out.update(bat.run())
        r3 = bat.submit(np.asarray([9, 9, 9, 9, 9], np.int32), 6)
        out.update(bat.run())
        assert set(out) == {r1, r2, r3}
        assert sentinel.compiles("speculative.draft_chunk") == draft_entries
        assert (
            sentinel.compiles("continuous.spec_verify") == verify_entries
        )
        snap = global_metrics().snapshot()
        assert "continuous.spec_acceptance" in snap["gauges"]
        assert (
            snap["histograms"]["continuous.spec_accepted_per_tick"][
                "count"
            ]
            >= 1
        )
        spans = {s.name for s in tracer.spans()}
        assert {"decode.draft", "decode.verify"} <= spans
        assert any(
            s.name == "decode.verify" and r1 in s.attrs["requests"]
            for s in tracer.spans()
        )
    finally:
        tracer.enabled = was_enabled


def test_spec_validation(lm_setup, draft_setup):
    lm, variables = lm_setup
    draft, dvars = draft_setup
    # temperature>0 is SERVED speculatively now (speculative sampling,
    # lossless in distribution) — the old greedy-only rejection was a
    # synchronous submit-time ValueError, so its absence is checked at
    # submit; the served streams themselves are covered end-to-end in
    # test_radix_fanout.py (no need to pay a spec compile here).
    bat = ContinuousBatcher(
        lm, variables, slots=2, draft_lm=draft, draft_variables=dvars
    )
    rid = bat.submit(np.asarray([1], np.int32), 2, temperature=0.7,
                     rng=jax.random.PRNGKey(0))
    assert bat.cancel(rid)
    with pytest.raises(ValueError, match="draft_variables"):
        ContinuousBatcher(lm, variables, slots=2, draft_lm=draft)
    with pytest.raises(ValueError, match="requires draft_lm"):
        ContinuousBatcher(
            lm, variables, slots=2, speculative=SpeculativeConfig()
        )
    with pytest.raises(ValueError, match="vocab"):
        other = lm_tiny(vocab=17, max_len=48)
        ovars = other.graph.init(
            jax.random.PRNGKey(3), jnp.zeros((1, 4), jnp.int32)
        )
        ContinuousBatcher(
            lm, variables, slots=2, draft_lm=other, draft_variables=ovars
        )
    with pytest.raises(ValueError, match="max_len"):
        short = lm_tiny(vocab=37, max_len=32)
        svars = short.graph.init(
            jax.random.PRNGKey(4), jnp.zeros((1, 4), jnp.int32)
        )
        ContinuousBatcher(
            lm, variables, slots=2, draft_lm=short, draft_variables=svars
        )
    # Spec + int8 caches is a supported composition now
    # (tests/test_quant_serving pins losslessness vs generate(int8)).
    bat = ContinuousBatcher(
        lm, variables, slots=2, kv_cache_dtype="int8",
        draft_lm=draft, draft_variables=dvars,
    )
    assert isinstance(bat._caches[0][0], tuple)
    with pytest.raises(ValueError, match="draft_k"):
        SpeculativeConfig(draft_k=0)


# -- slow parameterizations: the batched-losslessness fuzz ---------------


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["slots", "paged"])
@pytest.mark.parametrize("perfect", [True, False])
def test_spec_fuzz_staggered_lossless(lm_setup, draft_setup, layout,
                                      perfect):
    """Randomized serving traffic against the speculative tick:
    staggered admits, retirements, cancels, mixed prompt lengths and
    step counts, perfect and adversarial drafts, dense and paged
    layouts — every surviving stream token-for-token equals its solo
    generate()."""
    lm, variables = lm_setup
    draft, dvars = draft_setup
    d_lm, d_vars = (lm, variables) if perfect else (draft, dvars)
    rng = np.random.RandomState(17 if perfect else 18)
    kw = (
        dict(kv_layout="paged", page_size=8)
        if layout == "paged"
        else {}
    )
    bat = ContinuousBatcher(
        lm, variables, slots=3, draft_lm=d_lm, draft_variables=d_vars,
        speculative=SpeculativeConfig(draft_k=3), **kw,
    )
    want, cancelled = {}, set()
    pending = []
    for i in range(12):
        n = int(rng.randint(1, 14))
        steps = int(rng.randint(1, 12))
        p = rng.randint(0, 37, size=n).astype(np.int32)
        pending.append((p, steps))
    submitted = {}
    out = {}
    k = 0
    while pending or submitted:
        # admit a burst of 0-2 requests
        for _ in range(int(rng.randint(0, 3))):
            if not pending:
                break
            p, steps = pending.pop()
            rid = bat.submit(p, steps)
            submitted[rid] = (p, steps)
        bat.tick()
        k += 1
        # occasionally cancel a live request
        if submitted and rng.rand() < 0.15:
            rid = list(submitted)[int(rng.randint(len(submitted)))]
            if bat.cancel(rid):
                cancelled.add(rid)
        with bat._cv:
            done_now = [r for r in submitted if r in bat._done]
        for r in done_now:
            want[r] = submitted.pop(r)
        assert k < 500
    out = bat.run()
    for rid, (p, steps) in want.items():
        got = out[rid]
        solo = _solo(lm, variables, p, steps)
        if rid in cancelled:
            np.testing.assert_array_equal(got, solo[: len(got)])
        else:
            np.testing.assert_array_equal(got, solo, err_msg=f"req {rid}")


@pytest.mark.slow
def test_spec_gqa_rope_window_paged_lossless(draft_setup):
    """The serving-era architecture knobs compose with batched
    speculation: a GQA + RoPE + sliding-window target served paged,
    with mid-request page recycling behind the window, still matches
    solo generate() per row."""
    vocab = 37
    lm = transformer_lm(vocab, 32, 2, 4, 48, max_len=48, kv_heads=2,
                        window=16, pos="rope")
    variables = lm.graph.init(
        jax.random.PRNGKey(50), jnp.zeros((1, 4), jnp.int32)
    )
    draft, dvars = draft_setup
    rng = np.random.RandomState(51)
    prompts = [rng.randint(0, vocab, size=n).astype(np.int32)
               for n in (3, 9, 17)]
    steps = [24, 12, 30]
    for d_lm, d_vars in ((lm, variables), (draft, dvars)):
        bat = ContinuousBatcher(
            lm, variables, slots=2, kv_layout="paged", page_size=8,
            draft_lm=d_lm, draft_variables=d_vars,
            speculative=SpeculativeConfig(draft_k=2),
        )
        ids = {bat.submit(p, s): i
               for i, (p, s) in enumerate(zip(prompts, steps))}
        out = bat.run()
        for rid, i in ids.items():
            np.testing.assert_array_equal(
                out[rid], _solo(lm, variables, prompts[i], steps[i]),
                err_msg=f"req {i} draft={'self' if d_lm is lm else 'adv'}",
            )
