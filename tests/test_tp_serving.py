"""Tensor-parallel continuous serving: a batcher sharded over a 4-device
sim mesh must be INVISIBLE in outputs — bit-identical greedy streams vs
the tp=1 batcher and the single-device ``generate()`` across staggered
admits/retires/cancels, on both KV layouts, including speculative mode —
while per-device KV bytes shrink to logical/tp, the two-program compile
footprint holds, and a steady-state tick still stages zero host arrays."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.config import ParallelConfig, SpeculativeConfig
from adapt_tpu.models.transformer_lm import generate, transformer_lm
from adapt_tpu.runtime.continuous import ContinuousBatcher


@pytest.fixture(scope="module")
def lm_setup():
    # GQA with kv_heads divisible by the tp=4 mesh: the KV cache's head
    # axis is what shards, so this is the shape class TP serving exists
    # for (heads=8 queries folding 2-per-KV-head on every shard).
    lm = transformer_lm(37, 32, 2, 8, 64, max_len=48, kv_heads=4,
                        name="tp_target")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture(scope="module")
def draft_setup():
    # Small independent draft; stays REPLICATED under TP by design.
    draft = transformer_lm(37, 16, 1, 1, 32, max_len=48, name="tp_draft")
    variables = draft.graph.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32)
    )
    return draft, variables


def _solo(lm, variables, prompt, steps, **kw):
    return np.asarray(
        generate(lm, variables, jnp.asarray(prompt)[None], steps, **kw)
    )[0]


def _bat(lm, variables, sim_mesh, tp, **kw):
    return ContinuousBatcher(
        lm, variables, mesh=sim_mesh(tp), parallel=ParallelConfig(tp=tp),
        **kw,
    )


def _staggered_run(bat, prompts, steps, cancel_idx=None):
    """Staggered admits + a mid-flight cancel; returns {req_id: idx} and
    the output dict."""
    ids = {}
    for i in range(2):
        ids[bat.submit(prompts[i], steps[i])] = i
    bat.tick()
    bat.tick()
    for i in range(2, len(prompts)):
        ids[bat.submit(prompts[i], steps[i])] = i
    cancelled = None
    if cancel_idx is not None:
        cancelled = next(r for r, i in ids.items() if i == cancel_idx)
        bat.tick()
        assert bat.cancel(cancelled)
    return ids, cancelled, bat.run()


@pytest.mark.parametrize("layout", ["slots", "paged"])
def test_tp4_bit_identical_to_tp1_staggered(lm_setup, sim_mesh, layout):
    """tp=4 and tp=1 batchers run the same staggered workload (admits,
    retirements, a mid-flight cancel): every stream is bit-identical
    between them AND equals its solo single-device generate(); the tp=4
    caches hold exactly logical/4 bytes per device."""
    lm, variables = lm_setup
    rng = np.random.RandomState(1)
    # Request 0 is long-running and admitted in the FIRST wave, so the
    # mid-flight cancel below always hits a slot-bound request (a
    # queued-cancel would return an empty stream and test nothing).
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (3, 9, 5, 12, 7)]
    steps = [20, 4, 8, 3, 6]
    kw = dict(slots=3, chunk=2)
    if layout == "paged":
        kw.update(kv_layout="paged", page_size=8)
    outs = {}
    for tp in (1, 4):
        bat = _bat(lm, variables, sim_mesh, tp, **kw)
        ids, cancelled, out = _staggered_run(
            bat, prompts, steps, cancel_idx=0
        )
        outs[tp] = {ids[r]: out[r] for r in ids}
        st = bat.stats()
        assert st["tp"] == tp
        assert st["cache_bytes_per_device"] * tp == st["cache_bytes"]
        assert st["active"] == 0
    for i in range(5):
        np.testing.assert_array_equal(
            outs[4][i], outs[1][i], err_msg=f"req {i}: tp4 != tp1"
        )
        solo = _solo(lm, variables, prompts[i], steps[i])
        if i == 0:  # cancelled mid-flight: partial prefix of solo
            got = outs[4][i]
            assert 0 < len(got) < steps[i]
            np.testing.assert_array_equal(got, solo[: len(got)])
        else:
            np.testing.assert_array_equal(
                outs[4][i], solo, err_msg=f"req {i}: tp4 != generate"
            )


@pytest.mark.parametrize("layout", ["slots", "paged"])
def test_tp4_speculative_lossless(lm_setup, draft_setup, sim_mesh, layout):
    """Batched speculation under tp=4 (target sharded, draft replicated)
    stays per-row lossless vs solo single-device generate() on both KV
    layouts, and the whole workload compiles exactly ONE verify variant
    (the tp4-vs-tp1 bitwise claim is pinned by the non-spec test above;
    a tp=1 spec batcher here would only re-pay its compiles)."""
    from adapt_tpu.utils.profiling import global_compile_sentinel

    lm, variables = lm_setup
    draft, dvars = draft_setup
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (4, 7, 2)]
    steps = [7, 9, 5]
    kw = dict(slots=2, draft_lm=draft, draft_variables=dvars,
              speculative=SpeculativeConfig(draft_k=3))
    if layout == "paged":
        kw.update(kv_layout="paged", page_size=8)
    sentinel = global_compile_sentinel()
    bat = _bat(lm, variables, sim_mesh, 4, **kw)
    before = sentinel.compiles("continuous.spec_verify")
    ids = {bat.submit(p, s): i
           for i, (p, s) in enumerate(zip(prompts, steps))}
    out = bat.run()
    assert 0.0 <= bat.stats()["spec_acceptance"] <= 1.0
    # Two-program steady state survives GSPMD: this batcher's whole
    # staggered workload compiled exactly ONE verify variant.
    assert sentinel.compiles("continuous.spec_verify") - before == 1
    for rid, i in ids.items():
        np.testing.assert_array_equal(
            out[rid], _solo(lm, variables, prompts[i], steps[i]),
            err_msg=f"req {i}",
        )


def test_tp4_two_programs_and_zero_h2d(lm_setup, sim_mesh):
    """The hot-path invariants survive sharding: across churn the tp=4
    batcher keeps the step-chunk program at ONE compiled variant (the
    compile sentinel's watch — GSPMD partitioning must not fork shapes),
    and a steady-state tick stages zero host arrays."""
    from adapt_tpu.utils.profiling import global_compile_sentinel

    lm, variables = lm_setup
    sentinel = global_compile_sentinel()
    bat = _bat(lm, variables, sim_mesh, 4, slots=2, chunk=2)
    before = sentinel.compiles("continuous.step_chunk")
    r1 = bat.submit(np.asarray([1, 2, 3], np.int32), 30)
    bat.tick()
    assert sentinel.compiles("continuous.step_chunk") - before == 1
    h0 = bat.stats()["h2d_transfers"]
    for _ in range(4):
        bat.tick()  # pure steady state under the mesh
    assert bat.stats()["h2d_transfers"] == h0
    entries = sentinel.compiles("continuous.step_chunk")
    # Churn: a second wave admits, retires, and re-admits — no variant
    # may be added to the decode program.
    r2 = bat.submit(np.asarray([5, 6], np.int32), 3)
    out = bat.run()
    r3 = bat.submit(np.asarray([9, 9, 9, 9], np.int32), 5)
    out.update(bat.run())
    assert set(out) == {r1, r2, r3}
    assert sentinel.compiles("continuous.step_chunk") == entries


def test_tp_memory_gauges_per_device(lm_setup, sim_mesh):
    """The memory sources split logical vs per-device bytes: dense
    memory.kv_bytes_per_device == kv_bytes / tp; paged
    memory.pool_bytes_per_device == pool_bytes / tp; the replicated
    draft's bytes stay logical."""
    lm, variables = lm_setup
    dense = _bat(lm, variables, sim_mesh, 4, slots=2)
    ms = dense._memory_stats()
    assert ms["memory.kv_bytes_per_device"] * 4 == ms["memory.kv_bytes"]
    paged = _bat(lm, variables, sim_mesh, 4, slots=2, kv_layout="paged",
                 page_size=8)
    ms = paged._memory_stats()
    assert (
        ms["memory.pool_bytes_per_device"] * 4 == ms["memory.pool_bytes"]
    )
    # tp=1 (and no-mesh) batchers report per-device == logical.
    flat = ContinuousBatcher(lm, variables, slots=2)
    ms = flat._memory_stats()
    assert ms["memory.kv_bytes_per_device"] == ms["memory.kv_bytes"]
    assert flat.stats()["tp"] == 1


def test_tp_validation(lm_setup, sim_mesh):
    """Config/mesh mismatches and indivisible models fail eagerly, by
    name — not as opaque GSPMD errors mid-admission."""
    lm, variables = lm_setup
    mesh = sim_mesh(4)
    with pytest.raises(ValueError, match="requires a mesh"):
        ContinuousBatcher(
            lm, variables, slots=2, parallel=ParallelConfig(tp=4)
        )
    with pytest.raises(ValueError, match="!= mesh"):
        ContinuousBatcher(
            lm, variables, slots=2, mesh=mesh,
            parallel=ParallelConfig(tp=2),
        )
    with pytest.raises(ValueError, match="axis"):
        ContinuousBatcher(
            lm, variables, slots=2, mesh=sim_mesh(4, axis="dp"),
        )
    with pytest.raises(ValueError, match="tp"):
        ParallelConfig(tp=0)
    # kv_heads=2 does not divide tp=4: the GQA-aware check fires.
    odd = transformer_lm(37, 32, 1, 4, 64, max_len=48, kv_heads=2,
                         name="tp_odd")
    ovars = odd.graph.init(
        jax.random.PRNGKey(2), jnp.zeros((1, 4), jnp.int32)
    )
    with pytest.raises(ValueError, match="KV"):
        ContinuousBatcher(odd, ovars, slots=2, mesh=mesh)


def test_tp_sampled_and_mixed_traffic(lm_setup, sim_mesh):
    """Sampled requests (per-request key schedules, top-k/top-p
    truncation) ride the sharded programs unchanged: each stream equals
    its solo generate() with the same knobs."""
    lm, variables = lm_setup
    p1 = np.asarray([1, 2, 3], np.int32)
    p2 = np.asarray([4, 5, 6, 7], np.int32)
    bat = _bat(lm, variables, sim_mesh, 4, slots=2)
    r1 = bat.submit(p1, 6, temperature=0.9, top_k=5,
                    rng=jax.random.PRNGKey(21))
    r2 = bat.submit(p2, 5)
    out = bat.run()
    np.testing.assert_array_equal(
        out[r1],
        _solo(lm, variables, p1, 6, temperature=0.9, top_k=5,
              rng=jax.random.PRNGKey(21)),
    )
    np.testing.assert_array_equal(out[r2], _solo(lm, variables, p2, 5))
