"""Fleet telemetry federation + per-request forensics (ISSUE 11).

Layers, one file:

- reservoir export + weighted merge units (fleet percentiles computed
  from the union of sources' decimating reservoirs, delta chaining
  that never double-counts, event seq-gap loss accounting);
- exporter satellites — dynamic dotted suffixes rendered as Prometheus
  LABELS with a parse test, non-finite floats sanitized to ``null`` on
  ``/metrics.json``, ``/healthz`` liveness fields, a client hanging up
  mid-scrape not killing the serving process;
- the HTTP-pull fallback: a lease advertising ``meta["telemetry"]``
  gets polled into the store;
- forensics — the acceptance bundle for a request that is preempted,
  journal-replayed and finishes: both lives, the preemption edge,
  exactly-once delivery accounting;
- concurrent ``/fleet/*`` scrapes while reports land;
- the two-process acceptance: a REAL remote worker subprocess pushes
  ``MSG_TELEMETRY`` reports to the dispatcher, ``/fleet/metrics``
  carries both processes' counters under role/worker labels,
  ``/debug/request/<id>`` spans both pids, and killing the worker
  flips its ``fleet.report_age_s`` staleness signal instead of
  freezing its gauges.
"""

import json
import os
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from adapt_tpu.utils.exporter import prometheus_text, serve_metrics
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.telemetry import (
    FederatedStore,
    TelemetryReporter,
    WeightedReservoir,
    assemble_request,
    global_federated_store,
    source_key,
)
from adapt_tpu.utils.tracing import (
    FlightRecorder,
    global_flight_recorder,
    global_tracer,
)
from conftest import spawn_worker_proc


@pytest.fixture
def clean_slate():
    global_metrics().reset()
    global_flight_recorder().clear()
    yield
    global_metrics().reset()
    global_flight_recorder().clear()


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.read().decode(), r.headers.get("Content-Type")


def _parse_prom(text: str) -> dict:
    """Strict-ish exposition parse: every line is HELP/TYPE or
    ``name[{labels}] value``; returns {(name, labels-frozenset): value}.
    The parse test the label satellite calls for."""
    out = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE ")), line
            continue
        sample, value = line.rsplit(" ", 1)
        if sample.endswith("}"):
            name, _, rest = sample.partition("{")
            labels = frozenset(rest[:-1].split(","))
        else:
            name, labels = sample, frozenset()
        assert "{" not in name and '"' not in name, line
        out[(name, labels)] = float(value)
    return out


# -- reservoir merge + report chaining --------------------------------------


def test_weighted_reservoir_merges_disjoint_sources():
    a, b = WeightedReservoir(), WeightedReservoir()
    a.add([1.0] * 100, 1)
    b.add([100.0] * 100, 1)
    p = WeightedReservoir.percentiles([a, b])
    assert p["p99"] == 100.0
    # Equal mass: the weighted p50 sits at the boundary.
    assert p["p50"] in (1.0, 100.0)
    # Weight dominance: 300 observations at stride 3 vs 10 at stride 1.
    c, d = WeightedReservoir(), WeightedReservoir()
    c.add([5.0] * 100, 3)
    d.add([50.0] * 10, 1)
    assert WeightedReservoir.percentiles([c, d])["p50"] == 5.0
    # Decimation keeps memory bounded and total weight roughly stable.
    e = WeightedReservoir()
    for _ in range(20):
        e.add(list(range(1000)), 1)
    assert len(e.samples) <= WeightedReservoir._CAP


def test_fleet_store_merges_and_never_double_counts():
    ra, rb = MetricsRegistry(), MetricsRegistry()
    for _ in range(100):
        ra.observe("h", 1.0)
    for _ in range(100):
        rb.observe("h", 100.0)
    ra.inc("c", 3)
    store = FederatedStore()
    store.attach_local("server", "a", registry=ra)
    store.attach_local("stage", "b", registry=rb)
    fl = store.fleet_snapshot()
    m = fl["merged"]["histograms"]["h"]
    assert m["count"] == 200
    assert m["min"] == 1.0 and m["max"] == 100.0
    assert m["p99"] == 100.0  # merged from BOTH reservoirs
    assert fl["merged"]["counters"]["c"] == 3
    # Second round: only the delta lands; a quiet round adds nothing.
    for _ in range(50):
        ra.observe("h", 1.0)
    ra.inc("c", 2)
    fl2 = store.fleet_snapshot()
    assert fl2["merged"]["histograms"]["h"]["count"] == 250
    assert fl2["merged"]["counters"]["c"] == 5
    fl3 = store.fleet_snapshot()
    assert fl3["merged"]["histograms"]["h"]["count"] == 250
    assert fl3["merged"]["counters"]["c"] == 5
    # Per-source view keeps role/worker identity + per-source numbers.
    key_a = source_key("server", "a", os.getpid())
    assert fl3["sources"][key_a]["counters"]["c"] == 5
    assert fl3["sources"][key_a]["histograms"]["h"]["count"] == 150
    store.close()


def test_reporter_ships_flight_events_with_seq_and_store_detects_loss():
    rec = FlightRecorder(capacity=64)
    reg = MetricsRegistry()
    rep = TelemetryReporter("stage", "w0", registry=reg, recorder=rec)
    rec.record("admit", request=1)
    rec.record("finish", request=1, tokens=3)
    store = FederatedStore()
    store.ingest(rep.collect())
    evs = store.events(request=1)
    assert [e["kind"] for e in evs] == ["admit", "finish"]
    assert all(e["source"].startswith("stage:w0:") for e in evs)
    # Incremental: a second collect ships only NEW events.
    rec.record("cancel", request=2)
    store.ingest(rep.collect())
    assert len(store.events()) == 3
    # A fabricated seq gap (events evicted before shipping) is counted
    # as loss, not silently presented as a complete stream.
    key = source_key("stage", "w0", os.getpid())
    report = {
        "v": 1,
        "source": {"role": "stage", "worker": "w0", "pid": os.getpid()},
        "seq": 99,
        "wall": time.time(),
        "counters": {},
        "gauges": {},
        "histograms": {},
        "events": [{"ts": time.time(), "kind": "admit", "seq": 50,
                    "data": {"request": 9}}],
        "spans": [],
    }
    store.ingest(report)
    assert store.sources()[key]["lost_events"] > 0
    # Malformed reports raise (the comm ingest site guards + counts).
    with pytest.raises(ValueError):
        store.ingest({"v": 999})


def test_fleet_events_order_on_the_wall_clock_across_sources():
    store = FederatedStore()
    t0 = time.time()

    def report(worker, events):
        return {
            "v": 1,
            "source": {"role": "stage", "worker": worker, "pid": 1},
            "seq": 1, "wall": t0, "counters": {}, "gauges": {},
            "histograms": {}, "events": events, "spans": [],
        }

    store.ingest(report("b", [
        {"ts": t0 + 0.2, "kind": "finish", "seq": 1, "data": {}},
    ]))
    store.ingest(report("a", [
        {"ts": t0 + 0.1, "kind": "admit", "seq": 1, "data": {}},
        {"ts": t0 + 0.3, "kind": "cancel", "seq": 2, "data": {}},
    ]))
    assert [e["kind"] for e in store.events()] == [
        "admit", "finish", "cancel",
    ]


def test_duplicate_and_gapped_reports_apply_exactly_once():
    """The push path retransmits frames whose send erred: a duplicate
    report must be dropped by seq (never double-counted), and a seq
    gap (backlog overflow) must be counted as lost reports."""
    store = FederatedStore()

    def report(seq):
        return {
            "v": 1,
            "source": {"role": "stage", "worker": "w0", "pid": 1},
            "seq": seq, "wall": time.time(),
            "counters": {"c": 1.0}, "gauges": {},
            "histograms": {
                "h": {"count": 1, "sum": 1.0, "min": 1.0, "max": 1.0,
                      "samples": [1.0], "stride": 1}
            },
            "events": [{"ts": time.time(), "kind": "admit",
                        "seq": seq, "data": {"request": seq}}],
            "spans": [],
        }

    key = source_key("stage", "w0", 1)
    store.ingest(report(1))
    store.ingest(report(2))
    store.ingest(report(2))  # retransmit
    src = store.sources()[key]
    assert src["duplicate_reports"] == 1
    fl = store.fleet_snapshot(refresh=False)
    assert fl["merged"]["counters"]["c"] == 2.0  # NOT 3.0
    assert fl["merged"]["histograms"]["h"]["count"] == 2
    assert len(store.events()) == 2
    # A gap (reports 3..5 lost to backlog overflow) is accounted.
    store.ingest(report(6))
    assert store.sources()[key]["lost_reports"] == 3
    assert store.fleet_snapshot(refresh=False)["merged"]["counters"][
        "c"
    ] == 3.0


def test_reporter_reopened_after_close_never_recounts():
    """close() then collect(): the reporter must NOT re-ship its
    cumulative totals as a delta (the obs_overhead federation config
    reuses one reporter across trials)."""
    reg = MetricsRegistry()
    reg.inc("c", 5)
    reg.observe("h", 1.0)
    rep = TelemetryReporter("bench", "b0", registry=reg)
    store = FederatedStore()
    store.ingest(rep.collect())  # first: cumulative
    rep.close()
    store.ingest(rep.collect())  # reopened: flagged, empty delta
    key = source_key("bench", "b0", os.getpid())
    fl = store.fleet_snapshot(refresh=False)
    assert fl["merged"]["counters"]["c"] == 5.0
    assert fl["merged"]["histograms"]["h"]["count"] == 1
    assert store.sources()[key]["degraded_reports"] == 1
    # And the chain is healthy again after the reopen round.
    reg.inc("c", 2)
    store.ingest(rep.collect())
    assert store.fleet_snapshot(refresh=False)["merged"]["counters"][
        "c"
    ] == 7.0
    rep.close()


def test_attach_local_replacement_does_not_deadlock():
    """Regression: replacing a local reporter closes the stale one,
    whose final snapshot runs the old registry's collectors — which
    include the store's own staleness collector re-entering the store
    lock. The close must happen OUTSIDE attach_local's lock hold."""
    store = FederatedStore()
    a, b = MetricsRegistry(), MetricsRegistry()
    a.register_collector(store.collector)
    store.attach_local("server", "s0", registry=a)
    store.fleet_snapshot()  # opens the stale reporter's window
    done: list = []
    t = threading.Thread(
        target=lambda: done.append(
            store.attach_local("server", "s0", registry=b)
        ),
        daemon=True,
    )
    t.start()
    t.join(timeout=5)
    assert done, "attach_local deadlocked replacing a local reporter"
    store.close()


# -- exporter satellites -----------------------------------------------------


def test_prometheus_renders_dynamic_suffixes_as_labels_and_parses():
    """Satellite: per-tenant / per-source dotted suffixes become
    labels, never baked-in metric names; counters ending _total don't
    double it; the whole document parses."""
    reg = MetricsRegistry()
    reg.set_gauge("scheduler.queue_depth.gold", 3)
    reg.set_gauge("scheduler.queue_depth.free", 7)
    reg.inc("slo.met_total.gold", 5)
    reg.inc("slo.missed_total.free", 2)
    reg.set_gauge("fleet.report_age_s.stage:w0:123", 1.5)
    reg.inc("scheduler.rejected_total", 4)
    reg.observe("lat_s", 0.25)
    text = prometheus_text(reg.snapshot())
    samples = _parse_prom(text)
    assert samples[
        ("adapt_scheduler_queue_depth", frozenset(['tenant="gold"']))
    ] == 3
    assert samples[
        ("adapt_scheduler_queue_depth", frozenset(['tenant="free"']))
    ] == 7
    assert samples[
        ("adapt_slo_met_total", frozenset(['tenant="gold"']))
    ] == 5
    assert samples[
        ("adapt_slo_missed_total", frozenset(['tenant="free"']))
    ] == 2
    assert samples[
        ("adapt_fleet_report_age_s",
         frozenset(['source="stage:w0:123"']))
    ] == 1.5
    # No baked-suffix spellings and no doubled _total anywhere.
    assert "adapt_scheduler_queue_depth_gold" not in text
    assert "adapt_slo_met_total_gold" not in text
    assert "_total_total" not in text
    assert samples[("adapt_scheduler_rejected_total", frozenset())] == 4
    # HELP/TYPE emit once per family even with several label values.
    assert text.count("# TYPE adapt_scheduler_queue_depth gauge") == 1
    # Histogram family keeps its base-name summary shape.
    assert samples[("adapt_lat_s_count", frozenset())] == 1


def test_metrics_json_sanitizes_non_finite_floats(clean_slate):
    reg = MetricsRegistry()
    reg.set_gauge("roofline.nan", float("nan"))
    reg.set_gauge("roofline.inf", float("inf"))
    reg.set_gauge("roofline.ninf", float("-inf"))
    reg.set_gauge("roofline.ok", 2.5)
    server = serve_metrics(port=0, registry=reg, store=FederatedStore())
    try:
        body, _ = _get(server.server_address[1], "/metrics.json")
        snap = json.loads(body)  # bare json.dumps would emit NaN here
        assert snap["gauges"]["roofline.nan"] is None
        assert snap["gauges"]["roofline.inf"] is None
        assert snap["gauges"]["roofline.ninf"] is None
        assert snap["gauges"]["roofline.ok"] == 2.5
    finally:
        server.shutdown()
        server.server_close()


def test_healthz_fields_and_midscrape_disconnect(clean_slate):
    reg = MetricsRegistry()
    reg.inc("x.completed", 2)
    server = serve_metrics(
        port=0, registry=reg, store=FederatedStore(), role="decode",
    )
    port = server.server_address[1]
    try:
        body, _ = _get(port, "/healthz")
        h = json.loads(body)
        assert h["ok"] is True
        assert h["pid"] == os.getpid()
        assert h["role"] == "decode"
        assert h["uptime_s"] >= 0.0
        # A scraper hanging up right after the request must not kill
        # (or traceback-wedge) the serving process: later scrapes work.
        for _ in range(3):
            s = socket.create_connection(("127.0.0.1", port))
            s.send(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
            s.close()
        time.sleep(0.05)
        text, _ = _get(port, "/metrics")
        assert "adapt_x_completed_total 2" in text
    finally:
        server.shutdown()
        server.server_close()


def test_http_pull_fallback_via_lease_metadata(clean_slate):
    """A process the dispatcher doesn't own advertises its exporter's
    /telemetry.json in its registry lease; the store polls it."""
    from adapt_tpu.control.registry import WorkerRegistry

    remote_reg = MetricsRegistry()
    remote_reg.inc("prefill.jobs", 7)
    remote_rec = FlightRecorder(capacity=16)
    remote_rec.record("admit", request=5)
    # The "remote" process's exporter (same pid here; the transport —
    # HTTP against an advertised URL — is exactly the cross-host one).
    rsrv = serve_metrics(
        port=0, registry=remote_reg, recorder=remote_rec,
        store=FederatedStore(), role="prefill", worker="pf0",
    )
    registry = WorkerRegistry()
    url = f"http://127.0.0.1:{rsrv.server_address[1]}/telemetry.json"
    registry.register(
        "prefill:pf0", meta={"role": "prefill", "telemetry": url},
        ttl_s=60.0,
    )
    store = FederatedStore()
    store.attach_registry(registry)
    try:
        fl = store.fleet_snapshot()
        src = [
            s for s in fl["sources"].values() if s["worker"] == "prefill:pf0"
        ]
        assert src, f"poll did not ingest: {list(fl['sources'])}"
        assert src[0]["counters"]["prefill.jobs"] == 7
        assert store.events(request=5)
    finally:
        rsrv.shutdown()
        rsrv.server_close()


# -- forensics ---------------------------------------------------------------


def test_forensic_bundle_preempted_journal_replayed_finished(
    clean_slate, tmp_path
):
    """Satellite acceptance: a request that is preempted, replayed
    from the JOURNAL, and finishes — the bundle shows both lives, the
    preemption edge (with the interrupted life's stamps), and
    exactly-once delivery accounting."""
    from adapt_tpu.config import SchedulerConfig, SLOSpec
    from adapt_tpu.control.journal import DispatcherJournal
    from adapt_tpu.models.transformer_lm import lm_tiny
    from adapt_tpu.runtime.continuous import ContinuousBatcher

    lm = lm_tiny(vocab=29, max_len=64)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    journal = DispatcherJournal(str(tmp_path / "wal"))
    bat = ContinuousBatcher(
        lm, variables, slots=1, chunk=4, journal=journal,
        scheduler=SchedulerConfig(
            preempt=True, preempt_ttft_fraction=0.5, degrade=False
        ),
    )
    delivered: dict[int, list] = {}

    def cb(rid, tok, idx):
        delivered.setdefault(rid, []).append((idx, tok))

    p_low = np.arange(10, dtype=np.int32) % 29
    p_hi = (np.arange(7, dtype=np.int32) * 3) % 29
    low = bat.submit(
        p_low, 20, slo=SLOSpec(tenant="free", priority=0), on_token=cb
    )
    bat.tick()
    bat.tick()
    assert len(delivered.get(low, [])) > 0
    hi = bat.submit(
        p_hi, 10,
        slo=SLOSpec(ttft_budget_s=1e-4, tenant="gold", priority=10),
        on_token=cb,
    )
    out = bat.run()

    store = FederatedStore()
    store.attach_local("server", "disp0")
    store.attach_journal(journal)
    bundle = assemble_request(low, store=store)
    # Both lives, via the admit edges.
    assert bundle["delivery"]["lives"] == 2
    assert len(bundle["lives"]) == 2
    # The preemption edge, naming who it yielded to, replayed from the
    # journal, with the interrupted life's stamps.
    assert len(bundle["preemptions"]) == 1
    pre = bundle["preemptions"][0]
    assert pre["for_request"] == hi
    assert pre["source"] == "journal"
    assert pre["tokens_discarded"] == len(delivered[low]) or (
        pre["tokens_discarded"] >= 1
    )
    assert pre.get("ttft_s") is not None  # first life's TTFT
    # Exactly-once delivery accounting: indices 0..n-1 each exactly
    # once and the finish edge's token count matches.
    idxs = [i for i, _ in delivered[low]]
    assert idxs == list(range(len(out[low])))
    assert bundle["delivery"]["finished"]
    assert bundle["delivery"]["tokens"] == len(out[low])
    assert bundle["delivery"]["ttft_s"] is not None
    assert len(bundle["delivery"]["life_stamps"]) == 2
    # Wall-clock ordering across the lifecycle: admit before preempt
    # before the second admit before finish.
    kinds = [e["kind"] for e in bundle["events"]]
    assert kinds.index("preempted") > kinds.index("admit")
    assert kinds[-1] == "finish"
    # Journal: done-marked at finish -> no longer pending.
    assert bundle["journal"] == {"pending": False, "meta": None}
    # The high-priority winner's own bundle exists too.
    hb = assemble_request(hi, store=store)
    assert hb["delivery"]["lives"] == 1
    assert hb["delivery"]["tokens"] == len(out[hi])
    bat.close()
    journal.close()
    store.close()


def test_fleet_scrapes_concurrent_with_reports(clean_slate):
    """Concurrent /fleet/* scrapes while reports land: every response
    parses, no torn merges."""
    store = FederatedStore()
    reg = MetricsRegistry()
    server = serve_metrics(
        port=0, registry=reg, store=store, role="server", worker="d0"
    )
    port = server.server_address[1]
    stop = threading.Event()
    errors: list = []

    def feeder():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                store.ingest({
                    "v": 1,
                    "source": {
                        "role": "stage", "worker": f"w{i % 3}", "pid": 1,
                    },
                    "seq": i, "wall": time.time(),
                    "counters": {"remote.stage_execs": 1.0},
                    "gauges": {"g": float(i)},
                    "histograms": {
                        "remote.stage_exec_s": {
                            "count": 2, "sum": 0.2, "min": 0.1,
                            "max": 0.1, "samples": [0.1, 0.1],
                            "stride": 1,
                        }
                    },
                    "events": [{
                        "ts": time.time(), "kind": "remote_exec",
                        "seq": i, "data": {"request": i},
                    }],
                    "spans": [],
                })
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    t = threading.Thread(target=feeder, daemon=True)
    t.start()
    try:
        for _ in range(10):
            text, _ = _get(port, "/fleet/metrics")
            _parse_prom(text)
            body, _ = _get(port, "/fleet/metrics.json")
            fl = json.loads(body)
            assert "merged" in fl and "sources" in fl
            body, _ = _get(port, "/fleet/events")
            json.loads(body)
    finally:
        stop.set()
        t.join(timeout=2)
        server.shutdown()
        server.server_close()
    assert not errors
    fl = store.fleet_snapshot()
    m = fl["merged"]["histograms"]["remote.stage_exec_s"]
    assert m["count"] > 0 and m["p50"] == pytest.approx(0.1)


# -- two-process acceptance --------------------------------------------------


def test_two_process_fleet_metrics_forensics_and_staleness(
    clean_slate, devices
):
    """Acceptance: dispatcher + a REAL worker subprocess pushing
    MSG_TELEMETRY. /fleet/metrics carries both processes' counters
    under role/worker labels with the worker's histogram percentiles
    present; /debug/request/<id> returns one bundle whose
    events/spans span both processes; killing the worker flips its
    fleet.report_age_s staleness signal. Capacity plane riding the
    same transports: the worker's MSG_TELEMETRY reports carry its
    stage book, a registry lease advertises another, and
    /fleet/capacity merges them with the local provider's — each
    replica labeled and aged per source, the killed worker's age
    growing instead of its book freezing silently."""
    from adapt_tpu.comm.remote import RemoteWorkerProxy
    from adapt_tpu.config import (
        FaultConfig,
        ObservabilityConfig,
        ServeConfig,
    )
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_tiny

    tracer = global_tracer()
    was_enabled = tracer.enabled
    tracer.clear()
    store = FederatedStore()  # fresh store; proxies feed the GLOBAL one

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    plan = partition(g, ["encoder_block_1"])  # 2 stages

    port = 17663
    os.environ["ADAPT_TPU_TRACE"] = "1"
    try:
        proc = spawn_worker_proc(
            "--port", str(port), "--heartbeat", "0.1",
            "--telemetry-s", "0.3",
        )
    finally:
        del os.environ["ADAPT_TPU_TRACE"]
    cfg = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=2.0,
            heartbeat_s=0.2,
            task_deadline_s=30.0,
            watchdog_period_s=0.2,
            startup_wait_s=15.0,
            configure_timeout_s=60.0,
        ),
        obs=ObservabilityConfig(trace_enabled=True),
    )
    disp = Dispatcher(plan, variables, config=cfg)
    disp.spawn_workers(devices[:1])  # stage 0 in-process
    proxy = RemoteWorkerProxy(
        "fleet-remote-0",
        ("127.0.0.1", port),
        disp.registry,
        disp.result_queue,
        model_config={
            "model": "vit_tiny",
            "num_classes": 10,
            "cuts": ["encoder_block_1"],
            "input_shape": [2, 32, 32, 3],
        },
        fault=cfg.fault,
    )
    disp.attach_worker(proxy)
    disp.start()
    from adapt_tpu.runtime.capacity import stage_book

    server = serve_metrics(
        port=0, role="server", worker="disp0",
        capacity_provider=lambda: stage_book(2),
    )
    http = server.server_address[1]
    gstore = global_federated_store()
    # A third capacity source: a registry lease advertising its book
    # in meta["capacity"] (the DisaggServer path, minus the server).
    gstore.attach_registry(disp.registry)
    disp.registry.register(
        "cap-lease-0", meta={"capacity": stage_book(1, backlog=3)},
        ttl_s=60.0,
    )
    try:
        proxy.start()
        proxy.configure(1, None, plan.extract_variables(variables)[1])
        fut = disp.submit(x)
        fut.result(timeout=60.0)
        rid = fut.request_id

        # Wait for at least one post-exec report from the worker
        # (pushes every ~0.3s on the dispatcher link's ping thread).
        deadline = time.monotonic() + 20.0
        wkey = None
        while time.monotonic() < deadline:
            for key, s in gstore.sources().items():
                if s["role"] == "stage" and s["worker"] == (
                    "fleet-remote-0"
                ):
                    wkey = key
            if wkey is not None:
                fl = gstore.fleet_snapshot()
                src = fl["sources"][wkey]
                if src["counters"].get("remote.stage_execs"):
                    break
            time.sleep(0.1)
        assert wkey is not None, "no telemetry report arrived"
        worker_pid = fl["sources"][wkey]["pid"]
        assert worker_pid != os.getpid()

        # /fleet/metrics: both processes' counters, role/worker
        # labels, and the worker histogram's percentiles (merged from
        # its shipped reservoir).
        text, _ = _get(http, "/fleet/metrics")
        samples = _parse_prom(text)
        exec_keys = [
            (n, lab) for (n, lab) in samples
            if n == "adapt_remote_stage_execs_total"
            and 'worker="fleet-remote-0"' in lab
        ]
        assert exec_keys and 'role="stage"' in next(iter(exec_keys))[1]
        disp_keys = [
            (n, lab) for (n, lab) in samples
            if n == "adapt_dispatcher_completed_total"
            and 'worker="disp0"' in lab and 'role="server"' in lab
        ]
        assert disp_keys, "dispatcher's own counters missing from fleet"
        assert ("adapt_remote_stage_exec_s_p99", frozenset()) in samples
        assert any(
            n == "adapt_remote_stage_exec_s_count" for n, _ in samples
        )

        # /fleet/events: the worker's remote_exec edge rode the report.
        body, _ = _get(http, "/fleet/events")
        evs = json.loads(body)["events"]
        assert any(
            e["kind"] == "remote_exec"
            and e["data"]["request"] == rid
            for e in evs
        )

        # /fleet/capacity: three replica books over three transports —
        # the worker's rode MSG_TELEMETRY, the lease one rides
        # registry meta, the local provider's rides its reporter —
        # each labeled and aged per source.
        body, ctype = _get(http, "/fleet/capacity")
        assert ctype.startswith("application/json")
        caps = json.loads(body)["replicas"]
        wcap = caps[wkey]
        assert wcap["via"] == "telemetry"
        assert wcap["book"]["kind"] == "stage"
        assert wcap["book"]["headroom"]["stages"] >= 1
        assert wcap["age_s"] < 5.0
        lease = caps["lease:cap-lease-0"]
        assert lease["via"] == "lease"
        assert lease["book"]["headroom"]["backlog"] == 3
        local = [
            c for k, c in caps.items()
            if c["via"] == "telemetry" and c["worker"] == "disp0"
        ]
        assert local and local[0]["book"]["headroom"]["stages"] == 2
        assert local[0]["pid"] == os.getpid()

        # Forensics: one bundle, both processes present.
        body, _ = _get(http, f"/debug/request/{rid}")
        bundle = json.loads(body)
        assert bundle["request"] == rid
        span_pids = {s["pid"] for s in bundle["spans"]}
        assert os.getpid() in span_pids
        assert worker_pid in span_pids, (
            f"expected both pids in bundle spans, got {span_pids}"
        )
        ev_sources = {
            e["source"] for e in bundle["events"]
        }
        assert any(k.startswith("stage:") for k in ev_sources)

        # Staleness: kill the worker; its report age grows past the
        # cadence instead of its gauges freezing silently.
        proc.kill()
        proc.wait(timeout=10)
        time.sleep(1.2)
        text, _ = _get(http, "/fleet/metrics")
        samples = _parse_prom(text)
        age = samples[
            ("adapt_fleet_report_age_s",
             frozenset([f'source="{wkey}"']))
        ]
        assert age > 0.9, f"staleness did not move: {age}"
        # ... and the parent's own /metrics carries the same signal.
        text, _ = _get(http, "/metrics")
        psamples = _parse_prom(text)
        assert psamples[
            ("adapt_fleet_report_age_s",
             frozenset([f'source="{wkey}"']))
        ] > 0.9
        # The killed worker's capacity book stays listed with a
        # GROWING age — a router sees staleness, not a frozen book.
        caps = json.loads(_get(http, "/fleet/capacity")[0])["replicas"]
        assert caps[wkey]["age_s"] > 0.9
    finally:
        server.shutdown()
        server.server_close()
        disp.shutdown()
        tracer.enabled = was_enabled
        tracer.clear()
        if proc.poll() is None:
            proc.terminate()
            proc.wait(timeout=10)
