"""IR + partitioner unit tests.

Correctness oracle (SURVEY.md §4 build note, test #1): composed stage
outputs must equal the un-partitioned model output exactly — the property
the reference never tests but its design depends on (``src/dag_util.py``).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.graph import (
    INPUT,
    InvalidCutError,
    LayerGraph,
    partition,
    valid_cut_points,
)
from adapt_tpu.graph.ir import Lambda
from adapt_tpu.graph.partition import balanced_cuts


def residual_mlp_graph(width=16, blocks=3):
    """A small DAG with residual joins: the minimal shape of the problem the
    reference's ``dag_util`` exists to solve (ResNet-style add joins)."""
    g = LayerGraph("res_mlp")
    g.add("embed", nn.Dense(width), INPUT)
    prev = "embed"
    for i in range(blocks):
        branch = g.add(f"block{i}_branch", nn.Dense(width), prev)
        prev = g.add(
            f"block{i}_out", Lambda(lambda a, b: jax.nn.relu(a + b), "addrelu"),
            (prev, branch),
        )
    g.add("head", nn.Dense(4), prev)
    return g


@pytest.fixture(scope="module")
def graph_and_vars():
    g = residual_mlp_graph()
    x = jnp.ones((2, 8))
    variables = g.init(jax.random.PRNGKey(0), x)
    return g, variables, x


def test_full_apply_shape(graph_and_vars):
    g, variables, x = graph_and_vars
    y = g.apply(variables, x)
    assert y.shape == (2, 4)


def test_eval_shapes(graph_and_vars):
    g, variables, x = graph_and_vars
    shapes = g.eval_shapes(variables, jax.ShapeDtypeStruct(x.shape, x.dtype))
    assert shapes["head"].shape == (2, 4)
    assert shapes["block1_out"].shape == (2, 16)


def test_topological_add_enforced():
    g = LayerGraph("bad")
    with pytest.raises(ValueError, match="unknown layer"):
        g.add("a", nn.Dense(3), "missing")


def test_duplicate_name_rejected():
    g = LayerGraph("dup")
    g.add("a", nn.Dense(3), INPUT)
    with pytest.raises(ValueError, match="duplicate"):
        g.add("a", nn.Dense(3), INPUT)


def test_valid_cut_points(graph_and_vars):
    g, _, _ = graph_and_vars
    cuts = valid_cut_points(g)
    # Branch layers are NOT valid cuts (the residual skip crosses them);
    # block outputs and embed are.
    assert "embed" in cuts
    for i in range(3):
        assert f"block{i}_out" in cuts
        assert f"block{i}_branch" not in cuts


@pytest.mark.parametrize(
    "cuts",
    [["block0_out"], ["embed", "block1_out"], ["block0_out", "block1_out", "block2_out"]],
)
def test_composed_stages_match_full_model(graph_and_vars, cuts):
    g, variables, x = graph_and_vars
    plan = partition(g, cuts)
    assert plan.num_stages == len(cuts) + 1
    stage_vars = plan.extract_variables(variables)
    y_full = g.apply(variables, x)
    y_composed = plan.compose(stage_vars, x)
    np.testing.assert_array_equal(np.asarray(y_full), np.asarray(y_composed))


def test_stage_coverage_disjoint_and_total(graph_and_vars):
    g, _, _ = graph_and_vars
    plan = partition(g, ["block0_out", "block2_out"])
    all_nodes = [n for s in plan.stages for n in s.node_names]
    assert sorted(all_nodes) == sorted(g.topo_order())
    assert len(all_nodes) == len(set(all_nodes))


def test_invalid_cut_rejected(graph_and_vars):
    g, _, _ = graph_and_vars
    with pytest.raises(InvalidCutError, match="skip connection"):
        partition(g, ["block1_branch"])


def test_unknown_cut_rejected(graph_and_vars):
    g, _, _ = graph_and_vars
    with pytest.raises(InvalidCutError, match="unknown cut"):
        partition(g, ["nope"])


def test_out_of_order_cuts_rejected(graph_and_vars):
    g, _, _ = graph_and_vars
    with pytest.raises(InvalidCutError):
        partition(g, ["block1_out", "block0_out"])


def test_balanced_cuts(graph_and_vars):
    g, variables, x = graph_and_vars
    cuts = balanced_cuts(g, 3)
    assert len(cuts) == 2
    plan = partition(g, cuts)  # must be a legal plan
    stage_vars = plan.extract_variables(variables)
    np.testing.assert_array_equal(
        np.asarray(plan.compose(stage_vars, x)), np.asarray(g.apply(variables, x))
    )


def test_stage_apply_jittable(graph_and_vars):
    g, variables, x = graph_and_vars
    plan = partition(g, ["block1_out"])
    stage_vars = plan.extract_variables(variables)
    s0 = jax.jit(plan.stage_apply(plan.stages[0]))
    s1 = jax.jit(plan.stage_apply(plan.stages[1]))
    y = s1(stage_vars[1], s0(stage_vars[0], x))
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(g.apply(variables, x)), rtol=1e-6
    )


def test_input_fanout_not_a_valid_cut():
    # INPUT consumed by two nodes: neither branch dominates; only the merge.
    g = LayerGraph("fan")
    g.add("a", nn.Dense(4), INPUT)
    g.add("b", nn.Dense(4), INPUT)
    g.add("c", Lambda(lambda p, q: p + q, "add"), ("a", "b"))
    g.add("d", nn.Dense(2), "c")
    assert valid_cut_points(g) == ["c"]
    with pytest.raises(InvalidCutError):
        partition(g, ["a"])


def test_output_cut_rejected(graph_and_vars):
    g, _, _ = graph_and_vars
    with pytest.raises(InvalidCutError, match="graph output"):
        partition(g, ["head"])


def test_balanced_cuts_partial_costs(graph_and_vars):
    g, _, _ = graph_and_vars
    costs = {n: 1.0 for n in g.topo_order() if "branch" in n}  # omit merges
    cuts = balanced_cuts(g, 2, costs=costs)
    assert len(cuts) == 1
    partition(g, cuts)


def test_balanced_cuts_too_many_stages(graph_and_vars):
    g, _, _ = graph_and_vars
    with pytest.raises(InvalidCutError):
        balanced_cuts(g, 20)


def test_compose_length_mismatch(graph_and_vars):
    g, variables, x = graph_and_vars
    plan = partition(g, ["block1_out"])
    sv = plan.extract_variables(variables)
    with pytest.raises(ValueError, match="stale plan"):
        plan.compose(sv[:1], x)


# -- architecture-by-value specs ---------------------------------------------


def _roundtrip(graph, x):
    """graph -> JSON -> graph; prove structural identity by running the
    ORIGINAL variables through the rebuilt graph (same node names, same
    module hyperparams => same variable trees, same outputs)."""
    import json

    import jax

    from adapt_tpu.graph.spec import graph_from_spec, graph_to_spec

    spec = json.loads(json.dumps(graph_to_spec(graph)))  # full wire trip
    rebuilt = graph_from_spec(spec)
    assert rebuilt.topo_order() == graph.topo_order()
    assert rebuilt.output == graph.output
    variables = graph.init(jax.random.PRNGKey(0), x)
    y_ref = graph.apply(variables, x)
    y = rebuilt.apply(variables, x)
    np.testing.assert_array_equal(np.asarray(y_ref), np.asarray(y))
    return rebuilt


def test_spec_roundtrip_vit_tiny():
    from adapt_tpu.models.vit import vit_tiny

    _roundtrip(vit_tiny(), jnp.ones((1, 32, 32, 3), jnp.float32))


def test_spec_roundtrip_resnet50():
    from adapt_tpu.models.resnet import resnet50

    # bf16 + s2d stem: dtype fields and stem variants must ship by value.
    _roundtrip(
        resnet50(num_classes=10, dtype=jnp.bfloat16, stem="s2d"),
        jnp.ones((1, 64, 64, 3), jnp.float32),
    )


def test_spec_roundtrip_efficientnet_b0():
    from adapt_tpu.models.efficientnet import efficientnet_b0

    # Exercises Callable act fields, float ratios and the "add" Lambda.
    _roundtrip(
        efficientnet_b0(num_classes=10), jnp.ones((1, 64, 64, 3), jnp.float32)
    )


def test_spec_rejects_unknown_lambda_and_foreign_imports():
    from adapt_tpu.graph.ir import Lambda, LayerGraph
    from adapt_tpu.graph.spec import graph_from_spec, graph_to_spec

    g = LayerGraph("bad")
    g.add("mystery", Lambda(lambda x: x * 3, "triple"))
    with pytest.raises(TypeError, match="LAMBDA_REGISTRY"):
        graph_to_spec(g)

    hostile = {
        "name": "evil",
        "output": "n",
        "nodes": [
            {
                "name": "n",
                "inputs": ["__input__"],
                "module": {"kind": "flax", "type": "os.system", "config": {}},
            }
        ],
    }
    with pytest.raises(ValueError, match="refusing to import"):
        graph_from_spec(hostile)
