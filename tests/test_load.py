"""Workload observability: windowed metrics, SLO tracking, the load
harness, and roofline accounting (ISSUE 7).

Four layers, one file:

- ``MetricsRegistry`` windowed snapshot deltas — monotonic counter
  deltas and PERCENTILE ISOLATION between windows (the reservoir-fork
  contract);
- SLO tracking in ``ContinuousBatcher`` — attainment counters/gauges,
  per-tenant verdicts, the ``slo_missed`` flight event, goodput
  accounting, the ``obs_timeline`` off switch, and the hot-path
  invariants (zero h2d per steady tick, no new compiled variants);
- the ``benchmarks/load`` harness — schedule determinism (identical
  request schedules AND token counts across runs) and the cancel-storm
  + concurrent-scrape stress (no lost lifecycle edges, no negative
  gauges);
- roofline gauges — XLA cost-analysis flops/bytes, MFU/MBU under
  explicit peaks, no jit-cache growth from pulling them, and no
  utilization claims on the bare CPU backend.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from adapt_tpu.config import SLOSpec
from adapt_tpu.models.transformer_lm import lm_tiny
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.profiling import (
    global_compile_sentinel,
    global_engine_obs,
    roofline_peaks,
)
from adapt_tpu.utils.tracing import global_flight_recorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks.load.harness import drive_phase, warmup  # noqa: E402
from benchmarks.load.workload import (  # noqa: E402
    WorkloadSpec,
    build_schedule,
    offered_tokens,
    preset,
    schedule_digest,
)


@pytest.fixture
def clean_slate():
    """Reset the process-global registry/recorder and restore the
    engine-obs gate (tests here flip it). gc first: batchers from
    earlier tests whose jit-cache pins were dropped must leave the
    weak source dicts before assertions about gauge presence."""
    import gc

    gc.collect()
    global_metrics().reset()
    global_flight_recorder().clear()
    eobs = global_engine_obs()
    was = eobs.enabled
    yield
    eobs.enabled = was
    global_metrics().reset()
    global_flight_recorder().clear()


@pytest.fixture
def isolated_roofline():
    """Snapshot + clear the global roofline-source registry for the
    duration of a test. Batchers from EARLIER MODULES can outlive
    their tests (a batcher's jit caches pin it, and module-boundary
    cache clearing does not reliably release it), and a surviving
    plain batcher keeps serving `engine.*.decode` gauges — which would
    break this module's presence/headline assertions. Restoring the
    saved dict re-registers whatever was there."""
    from adapt_tpu.utils import profiling as prof

    with prof._MEMORY_LOCK:
        saved = dict(prof._ROOFLINE_SOURCES)
        prof._ROOFLINE_SOURCES.clear()
    yield
    with prof._MEMORY_LOCK:
        prof._ROOFLINE_SOURCES.clear()
        prof._ROOFLINE_SOURCES.update(saved)


@pytest.fixture
def batcher_factory():
    """Build tiny batchers and CLOSE them at teardown — a batcher's jit
    caches pin it alive, so an unclosed one keeps serving memory and
    roofline gauges into every later test's scrapes."""
    made = []

    def make(draft: bool = False, **kw):
        lm = lm_tiny(vocab=29, max_len=64)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        if draft:
            kw.update(draft_lm=lm, draft_variables=variables)
        bat = ContinuousBatcher(
            lm, variables, slots=2, chunk=4, **kw
        )
        made.append(bat)
        return bat

    yield make
    for b in made:
        b.close()


# -- windowed snapshot deltas ----------------------------------------------


def test_window_counter_deltas_are_monotonic_chunks():
    reg = MetricsRegistry()
    reg.inc("c", 5)
    s = reg.snapshot(window=True)
    assert s["counters"]["c"] == 5  # window=True still reports cumulative
    total = 5.0
    for chunk in (3.0, 7.0, 0.0, 11.0):
        reg.inc("c", chunk)
        total += chunk
        s = reg.snapshot(since=s, window=True)  # chain: close + reopen
        assert s["counters"]["c"] == chunk  # exactly this window's delta
        assert s["window_s"] >= 0.0
    reg.snapshot(since=s)  # final read closes the last window
    assert reg.snapshot()["counters"]["c"] == total
    assert not reg._windows  # a finished chain leaves no open window


def test_window_percentile_isolation():
    reg = MetricsRegistry()
    # Warm-up phase: a thousand tiny samples that would pin cumulative
    # percentiles near zero forever.
    for _ in range(1000):
        reg.observe("lat", 0.001)
    s = reg.snapshot(window=True)
    for _ in range(100):
        reg.observe("lat", 1.0)
    win = reg.snapshot(since=s, window=True)
    # The window sees ONLY its own phase's samples...
    assert win["histograms"]["lat"]["count"] == 100
    assert win["histograms"]["lat"]["p50"] == 1.0
    assert win["histograms"]["lat"]["min"] == 1.0
    # ...while the cumulative view still reflects the whole stream.
    cum = reg.snapshot()
    assert cum["histograms"]["lat"]["count"] == 1100
    assert cum["histograms"]["lat"]["p50"] < 1.0
    # Next chained window starts empty again.
    reg.observe_many("lat", [2.0, 4.0])
    win2 = reg.snapshot(since=win)
    assert win2["histograms"]["lat"]["count"] == 2
    assert win2["histograms"]["lat"]["min"] == 2.0


def test_window_requires_window_snapshot_and_eviction_is_flagged():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.snapshot(since=reg.snapshot())
    # Open enough windows to evict the first, then read it: degraded
    # (cumulative) histograms must be FLAGGED, not silent.
    first = reg.snapshot(window=True)
    for _ in range(MetricsRegistry._MAX_WINDOWS + 1):
        reg.snapshot(window=True)
    reg.observe("h", 1.0)
    out = reg.snapshot(since=first)
    assert out.get("window_evicted") is True


def test_plain_snapshot_shape_unchanged_and_costs_no_window():
    reg = MetricsRegistry()
    reg.inc("c")
    reg.observe("h", 1.0)
    snap = reg.snapshot()
    assert set(snap) == {"counters", "gauges", "histograms"}
    json.dumps(snap)  # exporter JSON contract
    assert not reg._windows  # plain scrapes never open windows


# -- SLO tracking in the batcher -------------------------------------------


def test_slo_met_missed_tenants_goodput_and_flight_event(clean_slate, batcher_factory):
    bat = batcher_factory()
    rng = np.random.RandomState(0)
    r_ok = bat.submit(
        rng.randint(0, 29, 6), 10,
        slo=SLOSpec(ttft_budget_s=60.0, itl_budget_s=60.0, tenant="gold"),
    )
    r_bad = bat.submit(
        rng.randint(0, 29, 6), 10,
        slo=SLOSpec(ttft_budget_s=1e-9, itl_budget_s=1e-9, tenant="best"),
    )
    bat.submit(rng.randint(0, 29, 6), 10)  # no SLO: nothing to violate
    bat.run()
    snap = global_metrics().snapshot()
    c = snap["counters"]
    assert c["slo.ttft_met_total"] == 1
    assert c["slo.ttft_missed_total"] == 1
    assert c["slo.met_total.gold"] == 1
    assert c["slo.missed_total.best"] == 1
    assert "slo.met_total.default" not in c  # SLO-less: no verdict
    g = snap["gauges"]
    assert g["slo.ttft_attainment"] == 0.5
    assert 0.0 < g["slo.itl_attainment"] < 1.0
    # Goodput: the busted request's tokens stop counting after its
    # first violation; the met + no-SLO requests' 20 all count.
    assert c["continuous.tokens_total"] == 30
    assert 20 <= c["continuous.good_tokens_total"] < 30
    assert "continuous.goodput_tokens_s" in g
    ev = global_flight_recorder().events("slo_missed")
    assert len(ev) == 1  # FIRST violation only, not one per commit
    assert ev[0]["data"]["request"] == r_bad
    assert ev[0]["data"]["tenant"] == "best"
    assert ev[0]["data"]["budget"] == "ttft"
    st = bat.stats()
    assert st["slo_ttft_met"] == 1 and st["slo_ttft_missed"] == 1
    assert r_ok != r_bad


def test_slo_obs_timeline_off_disables_everything(clean_slate, batcher_factory):
    bat = batcher_factory()
    bat.obs_timeline = False
    bat.submit(
        np.arange(4, dtype=np.int32) % 29, 8,
        slo=SLOSpec(ttft_budget_s=1e-9, itl_budget_s=1e-9),
    )
    bat.run()
    snap = global_metrics().snapshot()
    assert not any(k.startswith("slo.") for k in snap["counters"])
    assert not any(k.startswith("slo.") for k in snap["gauges"])
    assert "continuous.tokens_total" not in snap["counters"]
    assert "continuous.goodput_tokens_s" not in snap["gauges"]
    assert not global_flight_recorder().events("slo_missed")


def test_slo_tracking_keeps_hot_path_invariants(clean_slate, batcher_factory):
    """Zero h2d per steady tick and a frozen compile footprint with SLO
    evaluation running on every commit — the acceptance pin that SLO
    tracking is pure host arithmetic."""
    bat = batcher_factory()
    rng = np.random.RandomState(1)
    for _ in range(2):
        bat.submit(
            rng.randint(0, 29, 6), 40,
            slo=SLOSpec(ttft_budget_s=0.5, itl_budget_s=0.25,
                        tenant="t"),
        )
    for _ in range(3):
        bat.tick()  # admission burst + compiles
    sent = global_compile_sentinel()
    h2d0 = bat.stats()["h2d_transfers"]
    compiles0 = sent.compiles("continuous.step_chunk")
    for _ in range(4):
        bat.tick()
    assert bat.stats()["h2d_transfers"] == h2d0
    # Footprint frozen ACROSS the SLO-evaluated ticks (absolute size is
    # module-history-dependent: the class-level jit cache keys on self).
    assert sent.compiles("continuous.step_chunk") == compiles0


# -- workload + harness ----------------------------------------------------


def test_schedule_is_seed_deterministic_and_heavy_tailed():
    spec = WorkloadSpec(
        rate_rps=64.0, duration_s=4.0, cancel_fraction=0.3,
        prompt_sigma=0.8, steps_sigma=0.8,
    )
    a = build_schedule(spec, seed=7)
    b = build_schedule(spec, seed=7)
    assert a == b
    assert schedule_digest(a) == schedule_digest(b)
    assert build_schedule(spec, seed=8) != a
    assert offered_tokens(a) == sum(x.steps for x in a)
    # Heavy tail: the longest request dwarfs the median.
    steps = sorted(x.steps for x in a)
    assert steps[-1] >= 3 * steps[len(steps) // 2]
    # Tenant skew: rank-0 tenant strictly dominates.
    from collections import Counter

    tenants = Counter(x.tenant for x in a)
    assert tenants["t0"] > tenants["t3"]
    cancels = [x for x in a if x.cancel_after is not None]
    assert cancels and all(
        1 <= x.cancel_after < max(x.steps, 2) for x in cancels
    )


def test_multi_turn_preset_chains_conversations():
    """The multi_turn preset re-enters each conversation with the whole
    history so far: every follow-up's prompt extends its predecessor's
    prompt + reply (the radix cache's partial-hit shape), arrives
    turn_gap_s later, keeps the tenant, and stays under prompt_max.
    Chaining is seed-deterministic and digest-visible."""
    spec = preset("multi_turn", duration_s=1.0)
    assert spec.turns > 1
    a = build_schedule(spec, seed=5)
    assert a == build_schedule(spec, seed=5)
    by_prompt = {x.prompt: x for x in a}
    chained = 0
    for x in a:
        for upto in range(len(x.prompt) - 1, 0, -1):
            prev = by_prompt.get(x.prompt[:upto])
            if prev is not None and prev is not x:
                assert len(x.prompt) >= len(prev.prompt) + prev.steps
                assert x.tenant == prev.tenant
                assert x.t >= prev.t + spec.turn_gap_s - 1e-9
                chained += 1
                break
    assert chained >= len(a) // 3  # most arrivals are follow-ups
    assert all(len(x.prompt) <= spec.prompt_max for x in a)
    assert all(x.group == -1 for x in a)  # no branching in this preset
    assert sorted(x.t for x in a) == [x.t for x in a]


def test_agent_trace_preset_groups_branch_sets():
    """The agent_trace preset fans every base arrival into `branches`
    identical-prompt copies tied by a shared Arrival.group — the
    submit_fanout unit the harness's --fanout arm consumes — and the
    group ids land in the schedule digest."""
    spec = preset("agent_trace", duration_s=1.0)
    assert spec.branches > 1
    a = build_schedule(spec, seed=5)
    assert len(a) % spec.branches == 0
    from collections import defaultdict

    groups = defaultdict(list)
    for x in a:
        assert x.group >= 0
        groups[x.group].append(x)
    for g in groups.values():
        assert len(g) == spec.branches
        assert len({(x.prompt, x.steps, x.t, x.tenant) for x in g}) == 1
    # group ids are digest-relevant: branch-width changes re-key runs.
    b = build_schedule(
        dataclasses.replace(spec, branches=2), seed=5
    )
    assert schedule_digest(a) != schedule_digest(b)


def test_drive_phase_token_counts_deterministic(clean_slate, batcher_factory):
    """Two fresh batchers, same schedule: identical per-request token
    counts (the acceptance criterion's determinism half — greedy
    streams are slot-scheduling-independent, cancels live in token
    space)."""
    spec = WorkloadSpec(
        rate_rps=24.0, duration_s=0.75, vocab=29,
        prompt_median=4, prompt_max=8, steps_median=8, steps_max=16,
        cancel_fraction=0.4, cancel_after_tokens=3,
        ttft_budget_s=5.0, itl_budget_s=5.0,
    )
    schedule = build_schedule(spec, seed=3)
    assert len(schedule) > 5
    reports = []
    for _ in range(2):
        bat = batcher_factory()
        warmup(bat, spec.vocab, spec.steps_max, spec.prompt_max)
        reports.append(drive_phase(bat, schedule, spec))
        bat.close()
    assert reports[0]["schedule_digest"] == reports[1]["schedule_digest"]
    assert reports[0]["token_counts"] == reports[1]["token_counts"]
    assert reports[0]["tokens_delivered"] == reports[1]["tokens_delivered"]
    assert reports[0]["cancelled"] == reports[1]["cancelled"] > 0
    # Cancelled requests stopped at their token-space mark exactly.
    for arr, n in zip(schedule, reports[0]["token_counts"]):
        if arr.cancel_after is not None and arr.steps > 1:
            assert n == arr.cancel_after
        else:
            assert n == arr.steps


def test_cancel_storm_with_concurrent_scrape(clean_slate, batcher_factory):
    """The satellite stress: ~50% of in-flight requests cancelled while
    /metrics and /debug/events are scraped concurrently. No lost
    lifecycle edges (every request admits AND finishes, ring eviction
    notwithstanding), no negative gauges, every scrape parses."""
    from adapt_tpu.utils.exporter import serve_metrics

    server = serve_metrics(port=0)
    port = server.server_address[1]
    stop = threading.Event()
    scrapes: list[dict] = []
    errors: list[Exception] = []

    def scraper():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics.json", timeout=10
                ) as r:
                    scrapes.append(json.loads(r.read()))
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/events", timeout=10
                ) as r:
                    json.loads(r.read())
            except Exception as e:  # noqa: BLE001 — assert after join
                errors.append(e)
                return

    t = threading.Thread(target=scraper, daemon=True)
    try:
        bat = batcher_factory()
        spec = WorkloadSpec(
            rate_rps=48.0, duration_s=1.0, vocab=29,
            prompt_median=4, prompt_max=8,
            steps_median=8, steps_max=16,
            cancel_fraction=0.5, cancel_after_tokens=2,
            ttft_budget_s=5.0, itl_budget_s=5.0,
        )
        schedule = build_schedule(spec, seed=11)
        warmup(bat, spec.vocab, spec.steps_max, spec.prompt_max)
        rec = global_flight_recorder()
        admits0 = rec.kind_counts().get("admit", 0)
        finishes0 = rec.kind_counts().get("finish", 0)
        t.start()
        report = drive_phase(bat, schedule, spec)
        stop.set()
        t.join(timeout=30)
        assert not errors, errors
        assert report["cancelled"] > len(schedule) // 4
        counts = rec.kind_counts()
        # Every scheduled request produced its admit and finish edge —
        # the cumulative books balance even if the ring overflowed.
        assert counts["admit"] - admits0 == len(schedule)
        assert counts["finish"] - finishes0 == len(schedule)
        assert sum(
            1 for e in rec.events("finish")
            if e["data"]["reason"] == "cancelled"
        ) > 0
        assert scrapes, "scraper never completed a scrape"
        for snap in [scrapes[-1], global_metrics().snapshot()]:
            for name, v in snap["gauges"].items():
                assert v >= 0.0, f"negative gauge {name}={v}"
            for name, v in snap["counters"].items():
                assert v >= 0.0, f"negative counter {name}={v}"
    finally:
        stop.set()
        server.shutdown()
        server.server_close()


# -- roofline accounting ----------------------------------------------------


def test_roofline_gauges_with_explicit_peaks(
    clean_slate, monkeypatch, isolated_roofline, batcher_factory
):
    monkeypatch.setenv("ADAPT_TPU_PEAK_FLOPS", "1e9")
    monkeypatch.setenv("ADAPT_TPU_PEAK_BYTES_S", "2e9")
    assert roofline_peaks() == (1e9, 2e9)
    bat = batcher_factory()
    global_engine_obs().enabled = True
    rng = np.random.RandomState(0)
    bat.submit(rng.randint(0, 29, 6), 30)
    for _ in range(3):
        bat.tick()
    sent = global_compile_sentinel()
    compiles0 = sent.compiles("continuous.step_chunk")
    snap = global_metrics().snapshot()
    g = snap["gauges"]
    assert g["engine.flops.decode"] > 0
    assert g["engine.bytes_accessed.decode"] > 0
    assert g["engine.mfu.decode"] > 0 and g["engine.mbu.decode"] > 0
    assert g["engine.mfu"] == g["engine.mfu.decode"]
    assert g["engine.mbu"] == g["engine.mbu.decode"]
    # Pulling cost analysis lowers WITHOUT compiling: the watched jit
    # cache must not grow (a roofline scrape must never read as a
    # recompile).
    assert sent.compiles("continuous.step_chunk") == compiles0
    bat.close()
    assert "engine.mbu" not in global_metrics().snapshot()["gauges"]


def test_roofline_cpu_makes_no_utilization_claims(
    clean_slate, monkeypatch, isolated_roofline, batcher_factory
):
    monkeypatch.delenv("ADAPT_TPU_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("ADAPT_TPU_PEAK_BYTES_S", raising=False)
    assert roofline_peaks() is None  # CPU backend: no honest peak
    bat = batcher_factory()
    global_engine_obs().enabled = True
    bat.submit(np.arange(4, dtype=np.int32) % 29, 12)
    for _ in range(2):
        bat.tick()
    g = global_metrics().snapshot()["gauges"]
    assert g["engine.flops.decode"] > 0  # bytes/flops still export
    assert "engine.mfu" not in g and "engine.mbu" not in g
    bat.close()


def test_spec_batcher_rooflines_verify_program(
    clean_slate, monkeypatch, isolated_roofline, batcher_factory
):
    monkeypatch.setenv("ADAPT_TPU_PEAK_FLOPS", "1e9")
    monkeypatch.setenv("ADAPT_TPU_PEAK_BYTES_S", "2e9")
    bat = batcher_factory(draft=True)
    global_engine_obs().enabled = True
    bat.submit(np.arange(4, dtype=np.int32) % 29, 12)
    for _ in range(2):
        bat.tick()
    g = global_metrics().snapshot()["gauges"]
    assert g["engine.flops.verify"] > 0
    assert g["engine.mbu.verify"] > 0
    assert "engine.flops.decode" not in g  # spec mode never runs it


# -- CI smoke wrapper (slow: subprocess pays full import + compiles) --------


@pytest.mark.slow
def test_load_smoke_driver_emits_gated_records():
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "load", "smoke.py"),
         "--seed", "0"],
        capture_output=True, text=True, timeout=480, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0
    recs = {}
    for ln in proc.stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            r = json.loads(ln)
            recs[r["metric"]] = r
    assert set(recs) == {"load_goodput_tokens_s", "load_slo_attainment"}
    for r in recs.values():
        assert "error" not in r, r
    assert recs["load_goodput_tokens_s"]["value"] > 0
    assert 0.0 <= recs["load_slo_attainment"]["value"] <= 1.0
    # The curve shape: goodput can never exceed what was offered —
    # "grows unboundedly" is the broken-accounting failure mode this
    # pins (at BOTH points; whether the overload point saturates on a
    # given box depends on its speed, so that is reported, not gated).
    low = recs["load_goodput_tokens_s"]
    assert low["value"] <= 1.05 * low["offered_tokens_s"]
    assert low["overload_goodput_tokens_s"] <= 1.05 * (
        low["overload_offered_tokens_s"]
    )