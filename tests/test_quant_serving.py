"""Quantized KV serving everywhere: int8 caches are a cache-layout
property every program family composes with — paged pools, speculative
verify, prefix caching, chunked prefill, tensor parallelism — not a
special mode of the dense slot path.

The contract stack: quantized batcher streams are bit-identical to the
same-quantized solo path (``generate(kv_cache_dtype="int8")``) on the
whole-prompt-prefill paths across staggered admits/retires/cancels on
BOTH layouts including speculative mode; top-1 agreement vs native fp32
stays above a bound; the hot-path invariants (zero h2d per steady tick,
two-program compile footprint) survive quantization; and the memory
gauges report the capacity win honestly (scale planes counted,
``memory.kv_bytes_ratio`` observable)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.config import ParallelConfig, SpeculativeConfig
from adapt_tpu.models.transformer_lm import (
    generate,
    lm_tiny,
    logits_full,
    transformer_lm,
)
from adapt_tpu.ops.quantize import (
    QuantizedTensor,
    dequantize_params,
    quantize_params,
)
from adapt_tpu.runtime.continuous import ContinuousBatcher


@pytest.fixture(scope="module")
def lm_setup():
    lm = lm_tiny(vocab=37, max_len=48)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture(scope="module")
def spec_setup():
    # Small spec-sized target + independent draft (the
    # test_continuous_spec sizing rationale: losslessness is a
    # scheduling property, tier-1 wall time is the budget).
    lm = transformer_lm(37, 32, 2, 2, 64, max_len=48, name="q_target")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    draft = transformer_lm(37, 16, 1, 1, 32, max_len=48, name="q_draft")
    dvars = draft.graph.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables, draft, dvars


def _solo(lm, variables, prompt, steps, **kw):
    return np.asarray(
        generate(lm, variables, jnp.asarray(prompt)[None], steps, **kw)
    )[0]


# -- quantized paged pools ---------------------------------------------------


def test_int8_paged_staggered_matches_generate_int8(lm_setup):
    """Quantized PAGED pools reproduce generate(kv_cache_dtype="int8")
    exactly across staggered admits/retires/cancels — the same
    invisibility bar the dense int8 layout is held to, now on the
    production layout."""
    lm, variables = lm_setup
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (3, 9, 5, 12, 7)]
    # Request 0 is long-running and admitted in the FIRST wave, so the
    # mid-flight cancel below always hits a slot-bound request.
    steps = [20, 4, 8, 3, 6]
    bat = ContinuousBatcher(
        lm, variables, slots=3, chunk=4, kv_layout="paged", page_size=16,
        kv_cache_dtype="int8",
    )
    ids = {}
    for i in range(2):
        ids[bat.submit(prompts[i], steps[i])] = i
    bat.tick()
    for i in range(2, 5):  # arrive while the first two are mid-decode
        ids[bat.submit(prompts[i], steps[i])] = i
    bat.tick()
    cancelled = next(r for r, i in ids.items() if i == 0)
    assert bat.cancel(cancelled)
    out = bat.run()
    assert set(out) == set(ids)
    for rid, i in ids.items():
        want = _solo(lm, variables, prompts[i], steps[i],
                     kv_cache_dtype="int8")
        if rid == cancelled:
            got = out[rid]
            assert 0 < len(got) < steps[i]
            np.testing.assert_array_equal(got, want[: len(got)])
        else:
            np.testing.assert_array_equal(
                out[rid], want, err_msg=f"req {i}"
            )
    st = bat.stats()
    assert st["pages_in_use"] == 0  # pairs drained back to the pool
    # int8 values + f32 scale planes vs f32 native: (hd + 4) / (4 * hd).
    hd = lm.graph.node(lm.block_names[0]).module.head_dim
    assert st["cache_bytes_ratio"] == pytest.approx((hd + 4) / (4 * hd))


def test_int8_paged_prefix_cache_reuses_quantized_pages(lm_setup):
    """Prefix-cached QUANTIZED pages carry their scales: the second
    admission shares the first's pages (hits counted) and reproduces
    the exact cached prefix — the stream still equals the solo
    quantized path for this workload."""
    lm, variables = lm_setup
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, 37, size=37).astype(np.int32)  # 2 full pages
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged", page_size=16,
        kv_cache_dtype="int8",
    )
    r1 = bat.submit(prompt, 5)
    out1 = bat.run()
    assert bat._pager.stats().cached == 2
    # The shared pages' SCALE plane is live (registered pages hold real
    # quantized prompt K/V, not zeros) — the reuse-stays-exact
    # precondition.
    k_scales = np.asarray(bat._caches[0][0][1])
    shared = [p for p in range(1, bat._pool_pages)
              if p in bat._pager._key_of]
    assert shared and all(k_scales[p].any() for p in shared)
    r2 = bat.submit(prompt, 5)
    out2 = bat.run()
    st = bat._pager.stats()
    assert st.prefix_hits == 2 and st.cached == 2
    want = _solo(lm, variables, prompt, 5, kv_cache_dtype="int8")
    np.testing.assert_array_equal(out1[r1], want)
    np.testing.assert_array_equal(out2[r2], want)


def test_int8_chunked_prefill_matches_generate_int8(lm_setup):
    """Chunked prefill over quantized pools: one page-chunk pass per
    tick, chunk K/V quantized at each write, greedy stream equal to the
    solo quantized path for this workload (the suffix passes attend the
    already-quantized window — documented fine print; greedy holds
    here)."""
    lm, variables = lm_setup
    rng = np.random.RandomState(12)
    short = rng.randint(0, 37, size=4).astype(np.int32)
    long_p = rng.randint(0, 37, size=40).astype(np.int32)
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=2, kv_layout="paged", page_size=16,
        prefill_chunk=16, kv_cache_dtype="int8",
    )
    r_short = bat.submit(short, 8)
    bat.tick()
    r_long = bat.submit(long_p, 4)
    bat.tick()  # long mid-prefill while short decodes
    assert bat.slots[1].pf_done >= 0
    out = bat.run()
    np.testing.assert_array_equal(
        out[r_short], _solo(lm, variables, short, 8, kv_cache_dtype="int8")
    )
    np.testing.assert_array_equal(
        out[r_long], _solo(lm, variables, long_p, 4, kv_cache_dtype="int8")
    )


def test_int8_top1_agreement_vs_fp32_both_layouts(lm_setup):
    """Quantization is allowed to perturb logits, not to wreck them:
    served int8 greedy streams agree with the native fp32 stream on the
    overwhelming majority of positions, on both layouts."""
    lm, variables = lm_setup
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (4, 7, 3)]
    agree, total = 0, 0
    for kw in ({}, {"kv_layout": "paged", "page_size": 16}):
        bat = ContinuousBatcher(
            lm, variables, slots=2, kv_cache_dtype="int8", **kw
        )
        ids = {bat.submit(p, 8): p for p in prompts}
        out = bat.run()
        for rid, p in ids.items():
            native = _solo(lm, variables, p, 8)
            agree += int((out[rid] == native).sum())
            total += 8
    assert total == 48
    assert agree / total >= 0.75, f"top-1 agreement {agree}/{total}"


def test_int8_paged_hot_path_invariants(lm_setup):
    """The hot-path contracts survive quantization: a steady-state int8
    paged tick stages ZERO host arrays, and churn (admit/retire/
    re-admit) adds no compiled variant to the decode program
    (sentinel-checked, the PR-4 public API)."""
    from adapt_tpu.utils.profiling import global_compile_sentinel

    lm, variables = lm_setup
    sentinel = global_compile_sentinel()
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=2, kv_layout="paged", page_size=16,
        kv_cache_dtype="int8",
    )
    before = sentinel.compiles("continuous.step_chunk")
    r1 = bat.submit(np.asarray([1, 2, 3], np.int32), 30)
    bat.tick()
    assert sentinel.compiles("continuous.step_chunk") - before == 1
    h0 = bat.stats()["h2d_transfers"]
    for _ in range(4):
        bat.tick()  # pure steady state over quantized pools
    assert bat.stats()["h2d_transfers"] == h0
    entries = sentinel.compiles("continuous.step_chunk")
    r2 = bat.submit(np.asarray([5, 6], np.int32), 3)
    out = bat.run()
    r3 = bat.submit(np.asarray([9, 9, 9, 9], np.int32), 5)
    out.update(bat.run())
    assert set(out) == {r1, r2, r3}
    assert sentinel.compiles("continuous.step_chunk") == entries


# -- quantized speculative verify --------------------------------------------


@pytest.mark.parametrize("layout", ["slots", "paged"])
def test_int8_spec_lossless_vs_generate_int8(spec_setup, layout):
    """Speculative decoding over int8 caches: the verify chunk
    quantizes its multi-token appends through the shared absmax scheme,
    so every stream equals the solo quantized greedy path
    token-for-token — whatever the draft proposes, on both layouts."""
    lm, variables, draft, dvars = spec_setup
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (3, 9, 5)]
    steps = [9, 14, 8]
    kw = (
        dict(kv_layout="paged", page_size=8) if layout == "paged" else {}
    )
    # Adversarial independent draft on both layouts; the perfect draft
    # (the target itself — exercises acceptance > 0, multi-token
    # commits) rides the dense layout only: acceptance depth is
    # layout-blind, and each extra spec batcher is a full compile bill
    # against the tier-1 wall-time budget.
    drafts = [(draft, dvars)]
    if layout == "slots":
        drafts.append((lm, variables))
    for d_lm, d_vars in drafts:
        bat = ContinuousBatcher(
            lm, variables, slots=2, kv_cache_dtype="int8",
            draft_lm=d_lm, draft_variables=d_vars,
            speculative=SpeculativeConfig(draft_k=3), **kw,
        )
        ids = {bat.submit(p, s): (p, s)
               for p, s in zip(prompts, steps)}
        out = bat.run()
        for rid, (p, s) in ids.items():
            np.testing.assert_array_equal(
                out[rid],
                _solo(lm, variables, p, s, kv_cache_dtype="int8"),
                err_msg=f"layout={layout} "
                        f"draft={'self' if d_lm is lm else 'adv'}",
            )
        assert 0.0 <= bat.stats()["spec_acceptance"] <= 1.0


def test_int8_spec_two_programs_zero_h2d(spec_setup):
    """The spec tick's fixed-shape contract holds under quantization:
    exactly ONE verify variant for the whole staggered workload and
    zero host arrays per steady-state tick."""
    from adapt_tpu.utils.profiling import global_compile_sentinel

    lm, variables, draft, dvars = spec_setup
    sentinel = global_compile_sentinel()
    bat = ContinuousBatcher(
        lm, variables, slots=2, kv_cache_dtype="int8",
        draft_lm=draft, draft_variables=dvars,
    )
    before = sentinel.compiles("continuous.spec_verify")
    r1 = bat.submit(np.asarray([1, 2, 3], np.int32), 30)
    bat.tick()
    assert sentinel.compiles("continuous.spec_verify") - before == 1
    h0 = bat.stats()["h2d_transfers"]
    for _ in range(4):
        bat.tick()
    assert bat.stats()["h2d_transfers"] == h0
    entries = sentinel.compiles("continuous.spec_verify")
    r2 = bat.submit(np.asarray([5, 6], np.int32), 3)
    out = bat.run()
    assert set(out) == {r1, r2}
    assert sentinel.compiles("continuous.spec_verify") == entries


# -- int8 draft weights ------------------------------------------------------


def test_int8_draft_weights_top1_agreement():
    """Blockwise int8 draft WEIGHTS (quantize_params/dequantize_params)
    perturb the draft's logits only slightly: top-1 agreement vs the
    f32 draft stays high over a full-sequence forward. (The served
    stream never depends on the draft — that's the losslessness test
    below — so agreement is purely an acceptance-rate economy.)"""
    draft = transformer_lm(37, 16, 1, 1, 32, max_len=48, name="agr_draft")
    dvars = draft.graph.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32)
    )
    qvars = quantize_params(dvars)
    # Matrix leaves quantized, 1-D (bias/LN) leaves untouched.
    leaves = jax.tree.leaves(
        qvars, is_leaf=lambda l: isinstance(l, QuantizedTensor)
    )
    assert any(isinstance(l, QuantizedTensor) for l in leaves)
    assert all(
        isinstance(l, QuantizedTensor) or l.ndim <= 1 for l in leaves
    )
    ids = jnp.asarray(
        [[1, 5, 9, 2, 8, 3, 7, 4, 6, 11, 13, 17, 22, 30, 35, 12]],
        jnp.int32,
    )
    lg32 = np.asarray(logits_full(draft, dvars, ids))
    lg8 = np.asarray(logits_full(draft, dequantize_params(qvars), ids))
    agreement = float((lg32.argmax(-1) == lg8.argmax(-1)).mean())
    assert agreement >= 0.8, f"top-1 agreement {agreement}"


def test_int8_draft_weights_serving_lossless(spec_setup):
    """draft_weight_dtype="int8": the batcher stores the draft's
    weights quantized (observable: QuantizedTensor leaves in
    _draft_variables) and every stream STILL equals solo generate() —
    draft quality moves acceptance, never tokens. Composes with int8
    target caches."""
    lm, variables, draft, dvars = spec_setup
    bat = ContinuousBatcher(
        lm, variables, slots=2, kv_cache_dtype="int8",
        draft_lm=draft, draft_variables=dvars,
        speculative=SpeculativeConfig(draft_k=3, draft_weight_dtype="int8"),
    )
    stored = jax.tree.leaves(
        bat._draft_variables,
        is_leaf=lambda l: isinstance(l, QuantizedTensor),
    )
    assert any(isinstance(l, QuantizedTensor) for l in stored)
    rng = np.random.RandomState(9)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32) for n in (3, 8)]
    ids = {bat.submit(p, 8): p for p in prompts}
    out = bat.run()
    for rid, p in ids.items():
        np.testing.assert_array_equal(
            out[rid], _solo(lm, variables, p, 8, kv_cache_dtype="int8")
        )
    with pytest.raises(ValueError, match="draft_weight_dtype"):
        SpeculativeConfig(draft_weight_dtype="fp4")


# -- memory accounting -------------------------------------------------------


def test_memory_kv_bytes_ratio_gauge(lm_setup):
    """memory.kv_bytes / pool_bytes count the scale planes, and
    memory.kv_bytes_ratio reports quantized ÷ native-equivalent on both
    layouts (1.0 for native batchers)."""
    lm, variables = lm_setup
    hd = lm.graph.node(lm.block_names[0]).module.head_dim
    want_ratio = (hd + 4) / (4 * hd)  # int8 + f32 scales vs f32 native

    native = ContinuousBatcher(lm, variables, slots=2)
    assert native._memory_stats()["memory.kv_bytes_ratio"] == 1.0

    dense = ContinuousBatcher(lm, variables, slots=2, kv_cache_dtype="int8")
    ms = dense._memory_stats()
    assert ms["memory.kv_bytes_ratio"] == pytest.approx(want_ratio)
    # Scale planes are INSIDE kv_bytes: values alone would be hd/(4hd).
    values_only = sum(
        x.nbytes for x in jax.tree.leaves(dense._caches)
        if x.dtype == jnp.int8
    )
    assert ms["memory.kv_bytes"] > values_only

    paged = ContinuousBatcher(
        lm, variables, slots=2, kv_layout="paged", page_size=16,
        kv_cache_dtype="int8",
    )
    ms = paged._memory_stats()
    assert ms["memory.kv_bytes_ratio"] == pytest.approx(want_ratio)
    assert "memory.pool_bytes" in ms
    native_paged = ContinuousBatcher(
        lm, variables, slots=2, kv_layout="paged", page_size=16
    )
    assert (
        native_paged._memory_stats()["memory.kv_bytes_ratio"] == 1.0
    )
    assert ms["memory.pool_bytes"] == pytest.approx(
        native_paged._memory_stats()["memory.pool_bytes"] * want_ratio
    )


# -- tensor parallelism ------------------------------------------------------


def test_tp4_quantized_pool_bytes_and_stream(sim_mesh):
    """tp=4 quantized POOLS (the paged layout — where both pytree
    members, int8 values and f32 scale planes, must head-shard
    together): per-device bytes == logical/4 exactly, and the quantized
    stream still equals the single-device solo quantized path. (The
    dense int8 strips ride the same ``_shard_kv`` tree.map — a second
    GSPMD batcher here would only re-pay its compiles.)"""
    lm = transformer_lm(37, 32, 2, 8, 64, max_len=48, kv_heads=4,
                        name="q_tp_target")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    p = np.asarray([1, 2, 3], np.int32)
    want = _solo(lm, variables, p, 6, kv_cache_dtype="int8")
    bat = ContinuousBatcher(
        lm, variables, slots=2, kv_cache_dtype="int8",
        kv_layout="paged", page_size=8,
        mesh=sim_mesh(4), parallel=ParallelConfig(tp=4),
    )
    rid = bat.submit(p, 6)
    out = bat.run()
    st = bat.stats()
    assert st["cache_bytes_per_device"] * 4 == st["cache_bytes"]
    # Every leaf shards: int8 values AND f32 scale planes both hold
    # 1/4 of their logical bytes per device.
    for leaf in jax.tree.leaves(bat._caches):
        assert leaf.addressable_shards[0].data.nbytes * 4 == leaf.nbytes
    np.testing.assert_array_equal(out[rid], want)
