"""Engine-tier observability (``utils.profiling`` + wiring): the
compile sentinel flags post-warmup jit-cache growth as a counted,
recorded event; memory gauges partition the paged pool exactly and
report dense strip bytes; tick-phase histograms are one-branch gated;
``logging.kv`` stays machine-parseable; and the perf-regression gate
fails injected regressions while passing within-tolerance runs."""

import json
import shlex
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from benchmarks import ci_gate
from adapt_tpu.models.transformer_lm import lm_tiny
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.utils import profiling
from adapt_tpu.utils.exporter import serve_metrics
from adapt_tpu.utils.logging import kv
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.profiling import (
    CompileSentinel,
    engine_collector,
    global_compile_sentinel,
    global_engine_obs,
    register_memory_source,
)
from adapt_tpu.utils.tracing import global_flight_recorder


@pytest.fixture(scope="module")
def lm_setup():
    lm = lm_tiny(vocab=37, max_len=64)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture
def isolated_memory_sources():
    """Empty the process memory-source table for one test: jit caches
    hold strong refs to ``self`` (static argnum), so batchers from
    earlier tests stay alive and would otherwise sum into the
    gauges."""
    saved = dict(profiling._MEMORY_SOURCES)
    profiling._MEMORY_SOURCES.clear()
    try:
        yield
    finally:
        profiling._MEMORY_SOURCES.clear()
        profiling._MEMORY_SOURCES.update(saved)


# -- logging.kv quoting -----------------------------------------------------


def test_kv_quotes_unparseable_values():
    line = kv(a="x y", b="k=v", n=5, empty="", q='say "hi"')
    assert line == 'a="x y" b="k=v" n=5 empty="" q="say \\"hi\\""'
    # The quoted form stays splittable by a standard shell-style lexer:
    # exactly one token per field, '=' intact inside values.
    parts = shlex.split(line)
    assert parts == ["a=x y", "b=k=v", "n=5", "empty=", 'q=say "hi"']
    # Backslashes must round-trip too (an unquoted a\b would shlex back
    # to 'ab'), and carriage returns are escaped like newlines.
    assert shlex.split(kv(path="a\\b")) == ["path=a\\b"]
    assert kv(err="40%\rdone") == 'err="40%\\rdone"'


def test_kv_plain_values_unquoted():
    assert kv(slot=3, ratio=0.25, name="worker-1") == (
        "slot=3 ratio=0.25 name=worker-1"
    )


# -- compile sentinel -------------------------------------------------------


def test_sentinel_flags_recompile_only_after_warmup():
    sent = CompileSentinel(warmup_samples=2)

    @jax.jit
    def toy(x):
        return x + 1

    sent.register("toy", toy)
    toy(jnp.zeros((2,), jnp.float32))
    assert sent.sample() == 0  # first sample: baseline read
    toy(jnp.zeros((3,), jnp.float32))  # growth inside warmup
    assert sent.sample() == 0
    assert sent.events == 0

    flight_before = len(global_flight_recorder().events("recompile"))
    counter_before = global_metrics().counter("engine.compile_events")
    toy(jnp.zeros((4,), jnp.float32))  # forced shape change, warmed
    assert sent.sample() == 1
    assert sent.events == 1
    assert (
        global_metrics().counter("engine.compile_events")
        == counter_before + 1
    )
    recompiles = global_flight_recorder().events("recompile")
    assert len(recompiles) == flight_before + 1
    assert recompiles[-1]["data"]["program"] == "toy"
    assert recompiles[-1]["data"]["new"] == 1
    # Gauge tracks the cache size through both expected and unexpected
    # growth.
    snap = global_metrics().snapshot()
    assert snap["gauges"]["engine.compiles.toy"] == 3.0
    assert sent.compiles("toy") == 3
    # Stability: no growth, no event.
    toy(jnp.zeros((4,), jnp.float32))
    assert sent.sample() == 0
    # A custom registry (serve_metrics(registry=...)) sampling AFTER
    # the event still converges to the cumulative counter — detection
    # is sentinel-global, not first-sampler-wins.
    reg2 = MetricsRegistry()
    sent.sample(reg2)
    assert reg2.counter("engine.compile_events") == 1.0
    assert reg2.snapshot()["gauges"]["engine.compiles.toy"] == 3.0


def test_sentinel_idle_scrapes_do_not_burn_warmup():
    """A program registered at startup and sampled while the process is
    idle (exporter scrapes) keeps its full grace window: warmup counts
    ACTIVE samples (size > 0) only, so the first real compiles are
    never flagged."""
    sent = CompileSentinel(warmup_samples=2)

    @jax.jit
    def toy(x):
        return x - 1

    sent.register("toy", toy)
    for _ in range(10):  # idle scrapes: cache size stays 0
        assert sent.sample() == 0
    toy(jnp.zeros((2,), jnp.float32))  # first activity
    toy(jnp.zeros((3,), jnp.float32))
    assert sent.sample() == 0  # first ACTIVE sample: inside warmup
    assert sent.events == 0


def test_sentinel_prunes_watch_when_owner_gone():
    sent = CompileSentinel()
    sent.register("gone", size_fn=lambda: 2)
    sent.register("alive", size_fn=lambda: 1)
    reg = MetricsRegistry()
    sent.sample(reg)
    assert "engine.compiles.gone" in reg.snapshot()["gauges"]
    sent.register("gone", size_fn=lambda: None)  # owner collected
    sent.sample(reg)
    assert sent.watched() == ["alive"]
    # The retired program's gauge is cleared, not served stale forever.
    gauges = reg.snapshot()["gauges"]
    assert "engine.compiles.gone" not in gauges
    assert gauges["engine.compiles.alive"] == 1.0


def test_sentinel_reregister_rearms_warmup():
    sent = CompileSentinel(warmup_samples=1)

    @jax.jit
    def toy(x):
        return x * 2

    sent.register("toy", toy)
    toy(jnp.zeros((2,), jnp.float32))
    sent.sample()
    sent.sample()  # warmed now
    sent.register("toy", toy)  # re-arm (a fresh instance's constructor)
    toy(jnp.zeros((5,), jnp.float32))
    assert sent.sample() == 0  # growth back inside the new warmup
    assert sent.events == 0


def test_sentinel_disarm_revokes_unconsumed_allowance():
    """A granter that retires before its planned re-lowering lands must
    be able to take the allowance back: leftover slack on the shared
    watch would silently absorb another instance's REAL phantom
    variant (the batcher's close() calls disarm with its full grant;
    consumed units are already subtracted, so the clamp at zero strips
    exactly the leftovers)."""
    sent = CompileSentinel(warmup_samples=1)

    @jax.jit
    def toy(x):
        return x + 3

    sent.register("toy", toy)
    toy(jnp.zeros((2,), jnp.float32))
    sent.sample()
    sent.sample()  # warmed
    sent.rearm("toy", expect=2)  # planned re-lowering, never lands
    sent.disarm("toy", expect=2)  # granter retires: full grant back
    toy(jnp.zeros((5,), jnp.float32))  # REAL phantom variant
    assert sent.sample() == 1, "revoked allowance still absorbed growth"
    assert sent.events == 1
    sent.disarm("toy", expect=5)  # over-disarm clamps at zero...
    sent.disarm("missing")  # ...and unknown names are a no-op
    toy(jnp.zeros((7,), jnp.float32))
    assert sent.sample() == 1  # clamp did not go negative


def test_batcher_forced_shape_change_fires_sentinel(lm_setup):
    """Acceptance pin: a forced shape change after warmup increments
    ``engine.compile_events`` and records a flight-recorder event —
    through the real serving path (a late sampled+top_k request
    compiles new decode/staging variants). The same batcher journey
    also pins the one-branch phase gate: no ``engine.phase.*_s``
    samples while ``obs_engine`` is off, one per phase per tick while
    on."""
    lm, variables = lm_setup
    sent = global_compile_sentinel()
    eo = global_engine_obs()
    assert eo.enabled is False  # process default: off
    old_warmup = sent.warmup_samples
    sent.warmup_samples = 3
    try:
        bat = ContinuousBatcher(lm, variables, slots=2, chunk=2)
        prompt = np.asarray([1, 2, 3], np.int32)
        r1 = bat.submit(prompt, 40)

        def phase_count(name):
            return (
                global_metrics().snapshot()["histograms"]
                .get(f"engine.phase.{name}_s", {}).get("count", 0)
            )

        phases = ("admit", "prefill", "decode", "commit", "update")
        before = {n: phase_count(n) for n in phases}
        for _ in range(3):  # gate off: no phase samples recorded
            bat.tick()
        for n, c in before.items():
            assert phase_count(n) == c, n
        eo.enabled = True
        try:
            for _ in range(3):  # past warmup, steady greedy decode
                bat.tick()
            for n, c in before.items():
                assert phase_count(n) >= c + 3, n
        finally:
            eo.enabled = False
        events_before = sent.events
        counter_before = global_metrics().counter("engine.compile_events")
        flight_before = len(global_flight_recorder().events("recompile"))
        # Forced shape change: first sampled top_k request compiles the
        # truncate decode variant (and a new key-bucket staging variant).
        bat.submit(
            prompt, 4, temperature=0.7, top_k=5,
            rng=jax.random.PRNGKey(3),
        )
        bat.tick()
        assert sent.events > events_before
        assert (
            global_metrics().counter("engine.compile_events")
            > counter_before
        )
        new_events = global_flight_recorder().events("recompile")[
            flight_before:
        ]
        assert any(
            e["data"]["program"].startswith("continuous.") for e in new_events
        )
        out = bat.run()  # drain
        assert r1 in out
    finally:
        sent.warmup_samples = old_warmup


# -- memory accounting ------------------------------------------------------


def test_paged_memory_gauges_partition_pool(
    lm_setup, isolated_memory_sources
):
    """Acceptance pin: after N paged admissions,
    ``memory.pages_used + memory.pages_free + memory.pages_cached``
    equals the (allocatable) pool size — mid-flight and after
    retirement — and prefix reuse surfaces in the bridged counters."""
    lm, variables = lm_setup
    pool_pages = 20
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=2, kv_layout="paged", page_size=8,
        pool_pages=pool_pages,
    )
    register_memory_source("continuous", bat)  # table was isolated
    reg = MetricsRegistry()
    reg.register_collector(engine_collector)

    def gauges():
        return reg.snapshot()["gauges"]

    prompt = np.asarray(list(range(1, 18)), np.int32)  # 2 full pages
    bat.submit(prompt, 12)
    bat.tick()  # admitted, mid-flight
    g = gauges()
    assert g["memory.pool_pages"] == float(pool_pages - 1)  # excl. trash
    assert g["memory.pages_used"] > 0
    assert (
        g["memory.pages_used"] + g["memory.pages_free"]
        + g["memory.pages_cached"]
        == g["memory.pool_pages"]
    )
    assert g["memory.pool_bytes"] > 0
    bat.run()
    # Second admission with the SAME prompt: full prompt pages are
    # reused from the retired request's cached pages.
    hist_before = (
        global_metrics().snapshot()["histograms"]
        .get("paged.pages_reused_per_admission", {}).get("count", 0)
    )
    bat.submit(prompt, 6)
    bat.run()
    g = gauges()
    assert (
        g["memory.pages_used"] + g["memory.pages_free"]
        + g["memory.pages_cached"]
        == g["memory.pool_pages"]
    )
    assert g["paged.prefix_hits"] >= 2  # both full prompt pages shared
    assert g["paged.prefix_misses"] >= 1  # the first, cold admission
    snap = global_metrics().snapshot()["histograms"][
        "paged.pages_reused_per_admission"
    ]
    assert snap["count"] >= hist_before + 1
    assert snap["max"] >= 2.0


def test_paged_memory_gauges_partition_with_cache_tier(
    lm_setup, isolated_memory_sources
):
    """Satellite pin (ISSUE 14): with the host tier attached, the HBM
    partition (used + free + cached == pool_pages) stays exact
    MID-FLIGHT while pages spill, and the spilled books are served as
    their own gauges (``memory.pages_spilled`` / ``memory.host_bytes``
    — a copy BELOW the pool, never double-counted in the
    partition)."""
    from adapt_tpu.config import CacheTierConfig

    lm, variables = lm_setup
    pool_pages = 12
    bat = ContinuousBatcher(
        lm, variables, slots=1, chunk=2, kv_layout="paged", page_size=8,
        pool_pages=pool_pages,
        cache_tier=CacheTierConfig(
            spill_pages_per_tick=16, readmit_pages_per_tick=16
        ),
    )
    register_memory_source("continuous", bat)
    reg = MetricsRegistry()
    reg.register_collector(engine_collector)

    def check_partition():
        g = reg.snapshot()["gauges"]
        assert (
            g["memory.pages_used"] + g["memory.pages_free"]
            + g["memory.pages_cached"]
            == g["memory.pool_pages"]
        )
        return g

    rng = np.random.RandomState(0)
    first = rng.randint(1, 30, size=17).astype(np.int32)
    bat.submit(first, 8)
    bat.tick()  # mid-flight
    check_partition()
    # Flood until the first prompt's registered pages spill, checking
    # the partition at every boundary the books move across.
    for _ in range(4):
        bat.submit(rng.randint(1, 30, size=17).astype(np.int32), 8)
        bat.run()
        check_partition()
    g = check_partition()
    assert g["memory.pages_spilled"] >= 1
    assert g["memory.host_bytes"] > 0
    assert g["memory.pages_spilled"] == float(bat._tier.pages)
    # Readmit on re-reference: partition still exact, spilled gauge
    # tracks the tier (readmitted pages STAY host-resident — MRU).
    bat.submit(first, 4)
    bat.run()
    g = check_partition()
    assert bat.stats()["tier_readmitted"] >= 1
    assert g["memory.pages_spilled"] == float(bat._tier.pages)
    bat.close()


def test_dense_memory_gauges_match_strip_shapes(
    lm_setup, isolated_memory_sources
):
    """Dense KV bytes must equal the configured strip shapes exactly:
    layers x (K,V) x slots x kv_heads x (max_len + 1 trash) x head_dim
    x itemsize."""
    lm, variables = lm_setup
    slots = 3
    bat = ContinuousBatcher(lm, variables, slots=slots, chunk=2)
    register_memory_source("continuous", bat)
    block0 = lm.graph.node(lm.block_names[0]).module
    expected = (
        len(lm.block_names)
        * 2
        * slots
        * block0.cache_heads
        * (lm.max_len + 1)
        * block0.head_dim
        * jnp.dtype(block0.dtype).itemsize
    )
    assert bat._memory_stats()["memory.kv_bytes"] == float(expected)
    reg = MetricsRegistry()
    reg.register_collector(engine_collector)
    assert reg.snapshot()["gauges"]["memory.kv_bytes"] == float(expected)
    # A second batcher SUMS; close() retires it from the gauges even
    # though its jit caches pin the instance alive (GC never fires).
    bat2 = ContinuousBatcher(lm, variables, slots=slots, chunk=2)
    register_memory_source("continuous", bat2)
    assert (
        reg.snapshot()["gauges"]["memory.kv_bytes"] == 2.0 * expected
    )
    bat2.close()
    assert reg.snapshot()["gauges"]["memory.kv_bytes"] == float(expected)
    # Gauges whose every source retired are REMOVED, not served stale:
    # a paged batcher's pool gauges disappear once it is closed.
    bat3 = ContinuousBatcher(
        lm, variables, slots=2, chunk=2, kv_layout="paged", page_size=8,
    )
    assert "memory.pool_pages" in reg.snapshot()["gauges"]
    bat3.close()
    gauges = reg.snapshot()["gauges"]
    assert "memory.pool_pages" not in gauges
    assert gauges["memory.kv_bytes"] == float(expected)  # bat remains


# -- regression gate --------------------------------------------------------


def test_ci_gate_compare_tolerances():
    base = {
        "tps": {"value": 10.0, "direction": "higher_better",
                "rel_tol": 0.1},
        "overhead": {"value": 0.0, "direction": "lower_better",
                     "abs_tol": 5.0},
    }
    ok = {"tps": {"value": 9.5}, "overhead": {"value": 4.9}}
    assert ci_gate.compare(ok, base) == []
    # Improvements never fail.
    better = {"tps": {"value": 12.0}, "overhead": {"value": -1.0}}
    assert ci_gate.compare(better, base) == []
    # Injected regressions fail, NAMING the metric.
    bad = {"tps": {"value": 8.5}, "overhead": {"value": 5.2}}
    regs = ci_gate.compare(bad, base)
    assert len(regs) == 2
    assert regs[0].startswith("overhead:")  # sorted by metric name
    assert regs[1].startswith("tps:")
    # A driver error record or a missing metric is always a regression.
    assert ci_gate.compare(
        {"tps": {"value": 10.0, "error": "boom"}, "overhead": {"value": 0}},
        base,
    ) != []
    assert any(
        "missing" in r
        for r in ci_gate.compare({"tps": {"value": 10.0}}, base)
    )
    # A crashed driver is keyed by driver name (no metric line was ever
    # printed): the missing-metric regression must surface its error
    # text, not hide the cause.
    regs = ci_gate.compare(
        {
            "tps": {"value": 10.0},
            "some_driver": {"value": 0.0, "error": "timed out after 600s"},
        },
        base,
    )
    assert any("overhead: missing" in r and "timed out" in r for r in regs)


def test_ci_gate_main_exit_codes(tmp_path, capsys):
    baseline = {
        "suite": {},
        "metrics": {
            "m": {"value": 5.0, "direction": "higher_better",
                  "rel_tol": 0.1}
        },
    }
    path = tmp_path / "base.json"
    path.write_text(json.dumps(baseline))
    rc = ci_gate.main(
        ["--baseline", str(path)], records={"m": {"value": 4.8}}
    )
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and report["ok"] is True
    rc = ci_gate.main(
        ["--baseline", str(path)], records={"m": {"value": 3.0}}
    )
    captured = capsys.readouterr()
    report = json.loads(captured.out.strip().splitlines()[-1])
    assert rc == 1 and report["ok"] is False
    assert report["regressions"] and "m:" in report["regressions"][0]
    assert "REGRESSION: m:" in captured.err
    # Re-baselining carries tolerances, takes the measured value.
    out = tmp_path / "new.json"
    rc = ci_gate.main(
        ["--baseline", str(path), "--write-baseline", str(out)],
        records={"m": {"value": 6.5}},
    )
    capsys.readouterr()
    assert rc == 0
    new = json.loads(out.read_text())
    assert new["metrics"]["m"]["value"] == 6.5
    assert new["metrics"]["m"]["rel_tol"] == 0.1


# -- exporter under live ticking --------------------------------------------


def test_exporter_scrape_concurrent_with_ticking_batcher(lm_setup):
    """Scrapes race a live serving loop: metrics mutate during
    serialization, the memory collector walks a pager the ticking
    thread is mutating, and the sentinel samples from both threads —
    every response must stay well-formed."""
    lm, variables = lm_setup
    bat = ContinuousBatcher(lm, variables, slots=2, chunk=2)
    server = serve_metrics(port=0)
    port = server.server_address[1]
    rng = np.random.RandomState(5)
    try:
        with bat:
            ids = [
                bat.submit(
                    rng.randint(1, 37, size=n).astype(np.int32), 40
                )
                for n in (3, 5, 7, 4)
            ]
            for _ in range(10):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10
                ) as r:
                    text = r.read().decode()
                assert "adapt_continuous_ticks_total" in text
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics.json", timeout=10
                ) as r:
                    snap = json.loads(r.read().decode())
                assert "gauges" in snap and "histograms" in snap
            # Engine-tier families are served on the existing exporter.
            assert any(
                g.startswith("engine.compiles.continuous.")
                for g in snap["gauges"]
            )
            assert "memory.kv_bytes" in snap["gauges"]
            for rid in ids:
                bat.result(rid, timeout=120.0)
    finally:
        server.shutdown()
        server.server_close()
