"""Observability-layer tests (ISSUE 2): decimating histogram reservoir,
span ring + Chrome-trace export, Prometheus exposition under concurrent
traffic, ContinuousBatcher request timelines on /metrics, the flight
recorder, and cross-process span stitching over a real remote worker."""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.comm.framing import MSG_DATA, MSG_RESULT, Message, recv_msg, send_msg
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.tracing import (
    FlightRecorder,
    Tracer,
    export_spans,
    global_flight_recorder,
    global_tracer,
)
from conftest import spawn_worker_proc


@pytest.fixture
def clean_obs():
    """Snapshot/restore the process-global tracer + flight recorder so
    tests that enable tracing can't leak span recording into the rest
    of the suite."""
    tracer = global_tracer()
    recorder = global_flight_recorder()
    was_enabled = tracer.enabled
    yield tracer, recorder
    tracer.enabled = was_enabled
    tracer.clear()
    recorder.clear()


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.read().decode(), r.headers.get("Content-Type")


# -- histogram reservoir ----------------------------------------------------


def test_histogram_reservoir_tracks_late_samples():
    """Regression (satellite 1): the old reservoir kept only the FIRST
    4096 observations, so percentiles froze at the warm-up distribution.
    The decimating reservoir must let late samples move p50/p99."""
    reg = MetricsRegistry()
    for _ in range(5000):
        reg.observe("lat", 1.0)
    warm = reg.snapshot()["histograms"]["lat"]
    assert warm["p99"] == 1.0
    for _ in range(5000):
        reg.observe("lat", 100.0)
    s = reg.snapshot()["histograms"]["lat"]
    assert s["count"] == 10000
    assert s["min"] == 1.0 and s["max"] == 100.0
    # The old code would report p99 == 1.0 forever (the second 5000
    # observations never entered the reservoir).
    assert s["p99"] == 100.0
    # Roughly half the reservoir mass is late: p25-ish stays early,
    # p75-ish must be late.
    h = reg._histograms["lat"]
    assert h.percentile(75) == 100.0
    assert h.percentile(25) == 1.0


def test_histogram_reservoir_bounded_memory():
    reg = MetricsRegistry()
    for i in range(100_000):
        reg.observe("lat", float(i % 977))
    h = reg._histograms["lat"]
    assert len(h._samples) <= 4096
    assert h.count == 100_000
    # Summary stays exact for count/sum/min/max regardless of decimation.
    s = h.summary()
    assert s["count"] == 100_000
    assert s["min"] == 0.0 and s["max"] == 976.0


def test_observe_many_matches_observe():
    reg = MetricsRegistry()
    reg.observe_many("h", [1.0, 2.0, 3.0])
    reg.observe_many("h", [])  # no-op, no lock churn
    s = reg.snapshot()["histograms"]["h"]
    assert s["count"] == 3 and s["sum"] == 6.0


# -- tracer ring ------------------------------------------------------------


def test_tracer_ring_overwrites_and_counts_drops():
    """Satellite 2: a full span buffer must RING (newest spans survive),
    not silently drop everything after capacity."""
    before = global_metrics().counter("tracer.spans_dropped")
    tr = Tracer(capacity=4)
    tr.enabled = True
    for i in range(10):
        with tr.span("s", i=i):
            pass
    spans = tr.spans("s")
    assert len(spans) == 4
    assert [s.attrs["i"] for s in spans] == [6, 7, 8, 9]  # newest kept
    assert tr.spans_dropped == 6
    # Mirrored into the process registry for /metrics.
    assert global_metrics().counter("tracer.spans_dropped") - before == 6


def test_tracer_disabled_records_nothing():
    tr = Tracer(capacity=8)
    assert tr.enabled is False
    with tr.span("s") as sp:
        assert sp is None
    assert tr.spans() == [] and tr.spans_dropped == 0


def test_chrome_trace_export_is_valid(clean_obs):
    """Satellite 5: to_chrome_trace() output is valid Chrome trace-event
    JSON — loads, and every event has ph/ts/pid (the structural contract
    Perfetto needs)."""
    tr = Tracer(capacity=16)
    tr.enabled = True
    with tr.span("outer", request=7):
        with tr.span("inner", request=7, stage=0):
            pass
    blob = json.dumps(tr.to_chrome_trace())
    doc = json.loads(blob)
    events = doc["traceEvents"]
    assert events, "no events exported"
    for ev in events:
        assert {"ph", "ts", "pid", "name"} <= set(ev)
    xs = [e for e in events if e["ph"] == "X"]
    assert len(xs) == 2
    for ev in xs:
        assert ev["dur"] >= 0.0
        assert ev["args"]["request"] == 7
        assert ev["tid"] != 0
    # Process metadata row present (Perfetto labels the track with it).
    assert any(e["ph"] == "M" and e["name"] == "process_name" for e in events)


def test_span_export_ingest_roundtrip_preserves_origin():
    """The stitching primitive: spans exported in one process ingest
    into another tracer keeping their pid/tid and wall-clock position."""
    src = Tracer(capacity=8)
    src.enabled = True
    with src.span("remote.stage_exec", request=3, attempt=1) as sp:
        time.sleep(0.01)
    exported = export_spans([sp, None])  # None entries skipped
    assert len(exported) == 1
    # Simulate arrival in a different process: alien pid survives.
    exported[0]["pid"] = 424242
    dst = Tracer(capacity=8)
    dst.enabled = True
    dst.ingest(exported)
    got = dst.spans("remote.stage_exec")
    assert len(got) == 1
    assert got[0].pid == 424242
    assert got[0].attrs["request"] == 3
    assert got[0].duration == pytest.approx(sp.duration, rel=0.05)
    trace = dst.to_chrome_trace()
    pids = {e["pid"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert pids == {424242}
    # Garbage tolerance: a corrupt annex (non-list JSON, junk entries)
    # must never raise out of ingest — it would kill a proxy read loop.
    before = global_metrics().counter("tracer.ingest_rejected")
    dst.ingest(None)
    dst.ingest(42)
    dst.ingest(["junk", {"name": "x"}, {"name": "ok", "t0": 1.0, "t1": 2.0}])
    assert len(dst.spans()) == 2  # only the well-formed entry landed
    assert global_metrics().counter("tracer.ingest_rejected") - before == 4


# -- framing annex ----------------------------------------------------------


def test_framing_annex_roundtrip_over_socketpair():
    """The flags-byte annex: rides before the payload, length-prefixed;
    payload content and the no-annex path are unchanged."""
    a, b = socket.socketpair()
    try:
        annex = json.dumps([{"name": "s", "t0": 1.0, "t1": 2.0}]).encode()
        payload = [b"\x01" * 1000, b"\x02" * 500]  # multi-part scatter
        t = threading.Thread(
            target=send_msg,
            args=(a, Message(MSG_RESULT, 1, 42, 0, payload, annex=annex)),
        )
        t.start()
        got = recv_msg(b)
        t.join()
        assert got.annex == annex
        assert bytes(got.payload) == b"\x01" * 1000 + b"\x02" * 500
        assert (got.msg_type, got.stage_index, got.request_id) == (
            MSG_RESULT, 1, 42,
        )
        # No annex -> flags 0 -> annex None on receive.
        t = threading.Thread(
            target=send_msg, args=(a, Message(MSG_DATA, 0, 1, 0, b"xy"))
        )
        t.start()
        got = recv_msg(b)
        t.join()
        assert got.annex is None and bytes(got.payload) == b"xy"
    finally:
        a.close()
        b.close()


# -- exporter ---------------------------------------------------------------


def test_prometheus_exposition_has_help_type_and_parses_under_load():
    """Satellite 3: # HELP/# TYPE lines present, and a scrape racing
    heavy observe() traffic returns parseable output every time."""
    from adapt_tpu.utils.exporter import serve_metrics

    reg = MetricsRegistry()
    reg.inc("burst.completed")
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            reg.observe("burst.latency_s", (i % 100) / 100.0)
            reg.inc("burst.completed")
            i += 1

    threads = [threading.Thread(target=hammer, daemon=True) for _ in range(4)]
    for t in threads:
        t.start()
    server = serve_metrics(port=0, registry=reg)
    try:
        port = server.server_address[1]
        text = ""
        for _ in range(10):
            text, ctype = _get(port, "/metrics")
            assert "text/plain" in ctype
            for line in text.splitlines():
                if not line:
                    continue
                if line.startswith("#"):
                    assert line.startswith(("# HELP ", "# TYPE ")), line
                    continue
                name, value = line.rsplit(" ", 1)
                float(value)  # every sample line parses
        assert "# TYPE adapt_burst_completed_total counter" in text
        assert "# TYPE adapt_burst_latency_s summary" in text
        assert "# TYPE adapt_burst_latency_s_p50 gauge" in text
        assert "adapt_burst_latency_s_count" in text
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=2)
        server.shutdown()
        server.server_close()


def test_exporter_trace_events_404_and_free_port(clean_obs):
    """Satellite 5: /trace.json + /debug/events endpoints, the 404 path,
    and port=0 free-port binding (two servers can't collide)."""
    from adapt_tpu.utils.exporter import serve_metrics

    tr = Tracer(capacity=8)
    tr.enabled = True
    with tr.span("stage_exec", request=1):
        pass
    rec = FlightRecorder(capacity=8)
    rec.record("admit", request=1)
    s1 = serve_metrics(port=0, tracer=tr, recorder=rec)
    s2 = serve_metrics(port=0, tracer=tr, recorder=rec)
    try:
        p1 = s1.server_address[1]
        p2 = s2.server_address[1]
        assert p1 != 0 and p2 != 0 and p1 != p2  # real, distinct ports

        body, ctype = _get(p1, "/trace.json")
        assert "application/json" in ctype
        doc = json.loads(body)
        assert any(
            e.get("ph") == "X" and e["name"] == "stage_exec"
            for e in doc["traceEvents"]
        )

        body, ctype = _get(p1, "/debug/events")
        assert "application/json" in ctype
        events = json.loads(body)["events"]
        assert events and events[-1]["kind"] == "admit"
        assert events[-1]["data"]["request"] == 1

        for bad in ("/nope", "/trace", "/debug"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(p1, bad)
            assert ei.value.code == 404
    finally:
        for s in (s1, s2):
            s.shutdown()
            s.server_close()


# -- flight recorder --------------------------------------------------------


def test_flight_recorder_ring_and_snapshot(tmp_path):
    rec = FlightRecorder(capacity=3)
    for i in range(5):
        rec.record("redispatch", request=i)
    evs = rec.events("redispatch")
    assert [e["data"]["request"] for e in evs] == [2, 3, 4]  # newest kept
    assert rec.events_dropped == 2
    assert rec.events("quarantine") == []
    snap = rec.snapshot()
    assert snap["dropped"] == 2 and len(snap["events"]) == 3
    path = rec.snapshot_to(str(tmp_path / "flight.json"))
    loaded = json.load(open(path))
    assert [e["data"]["request"] for e in loaded["events"]] == [2, 3, 4]
    assert all("ts" in e and "kind" in e for e in loaded["events"])
    # A writer that recorded a non-JSON value must not make the dump
    # (or the /debug/events scrape) raise: default=str degrades it.
    rec.record("weird", err=ValueError("boom"), arr=np.float32(1.5))
    path = rec.snapshot_to(str(tmp_path / "flight2.json"))
    loaded = json.load(open(path))
    assert "boom" in loaded["events"][-1]["data"]["err"]


# -- continuous batcher request timelines -----------------------------------


def test_batcher_slo_histograms_on_metrics(clean_obs):
    """Acceptance: after a ContinuousBatcher run, TTFT / inter-token
    latency / queue-wait histograms are on /metrics with counts that
    match the completed requests."""
    from adapt_tpu.models.transformer_lm import lm_tiny
    from adapt_tpu.runtime.continuous import ContinuousBatcher
    from adapt_tpu.utils.exporter import serve_metrics

    global_metrics().reset()
    recorder = global_flight_recorder()
    recorder.clear()
    lm = lm_tiny(vocab=29, max_len=64)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    bat = ContinuousBatcher(lm, variables, slots=4, chunk=4)
    assert bat.obs_timeline  # default ON — leave-on instrumentation
    n_req, steps = 3, 5
    rng = np.random.RandomState(0)
    ids = [bat.submit(rng.randint(0, 29, size=4), steps) for _ in range(n_req)]
    done = bat.run()
    assert sorted(done) == sorted(ids)

    snap = global_metrics().snapshot()
    hists = snap["histograms"]
    assert hists["continuous.ttft_s"]["count"] == n_req
    assert hists["continuous.queue_wait_s"]["count"] == n_req
    # Every token after a request's first is one inter-token gap.
    assert hists["continuous.itl_s"]["count"] == n_req * (steps - 1)
    assert hists["continuous.request_latency_s"]["count"] == n_req
    assert snap["counters"]["continuous.completed"] == n_req
    # TTFT <= full latency, pairwise distributions are sane.
    assert hists["continuous.ttft_s"]["max"] <= (
        hists["continuous.request_latency_s"]["max"]
    )

    # Lifecycle events landed in the flight recorder.
    admits = recorder.events("admit")
    finishes = recorder.events("finish")
    assert len(admits) == n_req and len(finishes) == n_req
    assert {e["data"]["request"] for e in admits} == set(ids)
    assert all(e["data"]["reason"] == "completed" for e in finishes)
    assert all(e["data"]["tokens"] == steps for e in finishes)

    # And the whole thing scrapes: histograms + the PR-1 staging gauge.
    server = serve_metrics(port=0)
    try:
        text, _ = _get(server.server_address[1], "/metrics")
        assert f"adapt_continuous_ttft_s_count {n_req}" in text
        assert f"adapt_continuous_itl_s_count {n_req * (steps - 1)}" in text
        assert f"adapt_continuous_queue_wait_s_count {n_req}" in text
        # Satellite 4 bridges: fused-staging transfer count and the
        # codec framing-copy counters ride as gauges.
        assert "adapt_continuous_h2d_transfers" in text
        assert "adapt_codec_copy_bytes" in text
        assert "adapt_codec_copy_calls" in text
    finally:
        server.shutdown()
        server.server_close()


def test_batcher_timeline_off_is_silent(clean_obs):
    from adapt_tpu.models.transformer_lm import lm_tiny
    from adapt_tpu.runtime.continuous import ContinuousBatcher

    global_metrics().reset()
    lm = lm_tiny(vocab=29, max_len=64)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    bat = ContinuousBatcher(lm, variables, slots=2, chunk=4)
    bat.obs_timeline = False  # the one-branch off switch
    bat.submit(np.array([1, 2, 3], np.int32), 4)
    bat.run()
    hists = global_metrics().snapshot()["histograms"]
    for name in (
        "continuous.ttft_s",
        "continuous.itl_s",
        "continuous.queue_wait_s",
        "continuous.request_latency_s",
    ):
        assert name not in hists


# -- cross-process span stitching -------------------------------------------


def test_remote_spans_stitch_into_single_trace(clean_obs, devices):
    """Acceptance: a two-stage pipeline run with a REAL remote worker
    process produces ONE stitched trace — spans recorded in the worker
    process ride back on the result frames (flags-byte annex), share the
    request's id with the dispatcher-side spans, and GET /trace.json is
    structurally Perfetto-loadable with both processes present."""
    from adapt_tpu.comm.remote import RemoteWorkerProxy
    from adapt_tpu.config import FaultConfig, ObservabilityConfig, ServeConfig
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_tiny
    from adapt_tpu.utils.exporter import serve_metrics

    tracer, _ = clean_obs
    tracer.clear()

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    plan = partition(g, ["encoder_block_1"])  # 2 stages
    assert plan.num_stages == 2

    port = 17661
    os.environ["ADAPT_TPU_TRACE"] = "1"  # worker process enables tracing
    try:
        proc = spawn_worker_proc("--port", str(port), "--heartbeat", "0.2")
    finally:
        del os.environ["ADAPT_TPU_TRACE"]
    cfg = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=2.0,
            heartbeat_s=0.2,
            task_deadline_s=30.0,
            watchdog_period_s=0.2,
            startup_wait_s=15.0,
            configure_timeout_s=60.0,
        ),
        obs=ObservabilityConfig(trace_enabled=True),
    )
    disp = Dispatcher(plan, variables, config=cfg)  # enables the tracer
    assert tracer.enabled
    disp.spawn_workers(devices[:1])  # stage 0 lives in-process
    proxy = RemoteWorkerProxy(
        "obs-remote-0",
        ("127.0.0.1", port),
        disp.registry,
        disp.result_queue,
        model_config={
            "model": "vit_tiny",
            "num_classes": 10,
            "cuts": ["encoder_block_1"],
            "input_shape": [2, 32, 32, 3],
        },
        fault=cfg.fault,
    )
    disp.attach_worker(proxy)
    disp.start()
    server = serve_metrics(port=0)
    try:
        proxy.start()
        # The remote owns stage 1 (only configured candidate for it).
        proxy.configure(1, None, plan.extract_variables(variables)[1])
        fut = disp.submit(x)
        y = fut.result(timeout=60.0)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(g.apply(variables, x)), rtol=1e-5
        )
        rid = fut.request_id

        body, _ = _get(server.server_address[1], "/trace.json")
        doc = json.loads(body)
        events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert events
        for ev in events:
            assert {"ph", "ts", "pid", "tid", "name", "dur"} <= set(ev)
        mine = [e for e in events if e["args"].get("request") == rid]
        names = {e["name"] for e in mine}
        # Dispatcher-side spans and the worker-process span, one trace.
        assert "dispatch.stage_rtt" in names
        assert "remote.stage_exec" in names
        assert "request" in names
        remote_execs = [e for e in mine if e["name"] == "remote.stage_exec"]
        assert any(e["args"]["stage"] == 1 for e in remote_execs)
        pids = {e["pid"] for e in mine}
        assert os.getpid() in pids
        assert len(pids) >= 2, (
            f"expected spans from both processes, got pids {pids}"
        )
        # attempt tags survive the wire.
        assert all(
            e["args"].get("attempt") == 0 for e in remote_execs
        )
    finally:
        server.shutdown()
        server.server_close()
        disp.shutdown()
        proc.terminate()
        proc.wait(timeout=10)
