"""Elastic mesh recovery (ISSUE 8): survive a chip loss in the TP
request tier with live KV resharding.

The contract under test, end to end:

- **detection** — ``DeviceHealthMonitor.kill`` (the simulated-kill
  injection point) revokes the device's membership lease; the batcher
  consumes the ``leave`` event at its next tick (or raises
  ``DeviceLostError`` under ``auto_reshard=False``);
- **re-lowering** — the mesh rebuilds from survivors (tp=4 -> tp=2),
  the program families re-lower with exactly ONE new variant each (no
  phantom variants, no sentinel recompile events), per-device KV bytes
  land at logical/2, and the steady-state tick goes back to staging
  zero host arrays;
- **live migration** — surviving in-flight greedy requests finish
  BIT-IDENTICAL to an uninterrupted tp=4 run (both KV layouts,
  speculative mode, int8 pools included);
- **replay** — non-migratable requests replay from the journal to
  identical tokens, re-entering through the paged prefix cache
  (``paged.prefix_hits`` increments) instead of a full re-prefill;
- **observability** — ``device_lost`` / ``mesh_reshard`` /
  ``kv_migrated`` / ``replayed_from_journal`` flight events with
  ``kind_counts()`` visibility, the ``recovery.wall_s`` histogram and
  ``recovery.{migrated,replayed,dropped}_total`` counters;
- **combined fault** (slow) — a device kill concurrent with a cancel
  storm and live /metrics.json + /debug/events scrapes: the lifecycle
  books balance, no gauge goes negative, every scrape parses.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from adapt_tpu.config import (
    ParallelConfig,
    RecoveryConfig,
    SLOSpec,
    SpeculativeConfig,
)
from adapt_tpu.control.journal import DispatcherJournal
from adapt_tpu.control.registry import DeviceHealthMonitor
from adapt_tpu.models.transformer_lm import generate, transformer_lm
from adapt_tpu.runtime.continuous import ContinuousBatcher, DeviceLostError
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.profiling import global_compile_sentinel
from adapt_tpu.utils.tracing import global_flight_recorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


@pytest.fixture(scope="module")
def lm_setup():
    # GQA with kv_heads divisible by tp=4 AND tp=2 — the divisor-shrink
    # class elastic recovery serves.
    lm = transformer_lm(37, 32, 2, 8, 64, max_len=48, kv_heads=4,
                        name="rec_target")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture(scope="module")
def draft_setup():
    draft = transformer_lm(37, 16, 1, 1, 32, max_len=48, name="rec_draft")
    variables = draft.graph.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32)
    )
    return draft, variables


def _solo(lm, variables, prompt, steps, **kw):
    return np.asarray(
        generate(lm, variables, jnp.asarray(prompt)[None], steps, **kw)
    )[0]


def _tp4(lm, variables, sim_mesh, health=None, **kw):
    return ContinuousBatcher(
        lm, variables, mesh=sim_mesh(4), parallel=ParallelConfig(tp=4),
        health=health, **kw,
    )


def _mesh_devices(bat):
    return list(bat._mesh.devices.flat)


PROMPTS = [
    np.asarray(p, np.int32)
    for p in ([1, 2, 3], [4, 5, 6, 7, 8, 9, 10, 11, 12], [13, 14, 15, 16, 17])
]
STEPS = [20, 14, 10]


def _run_workload(bat, kill_device=None, monitor=None):
    """Staggered admits; optionally kill one mesh device after the
    third request's first tick (every request slot-bound and
    mid-stream); run to drain."""
    ids = [bat.submit(PROMPTS[0], STEPS[0]), bat.submit(PROMPTS[1], STEPS[1])]
    bat.tick()
    bat.tick()
    ids.append(bat.submit(PROMPTS[2], STEPS[2]))
    bat.tick()  # admit the third: all three decoding at kill time
    if kill_device is not None:
        monitor.kill(kill_device)
    out = bat.run()
    return [out[r] for r in ids]


@pytest.mark.parametrize("layout", ["slots", "paged"])
def test_kill_midstream_bit_identical(lm_setup, sim_mesh, layout):
    """THE acceptance pin: kill one device of the tp=4 mesh mid-stream;
    every surviving in-flight greedy request finishes bit-identical to
    the uninterrupted tp=4 run AND to solo generate(), on both KV
    layouts; per-device KV bytes land at logical/2 on the shrunk
    mesh."""
    lm, variables = lm_setup
    kw = dict(slots=3, chunk=2)
    if layout == "paged":
        kw.update(kv_layout="paged", page_size=8)
    base_bat = _tp4(lm, variables, sim_mesh, **kw)
    base = _run_workload(base_bat)
    base_bat.close()
    mon = DeviceHealthMonitor()
    bat = _tp4(lm, variables, sim_mesh, health=mon, **kw)
    got = _run_workload(bat, kill_device=_mesh_devices(bat)[3], monitor=mon)
    st = bat.stats()
    assert st["tp"] == 2
    assert st["recoveries"] == 1
    assert st["recovery_migrated"] == 3  # all three were decoding
    assert st["recovery_replayed"] == 0
    assert st["recovery_dropped"] == 0
    assert st["last_recovery_wall_s"] > 0.0
    assert st["cache_bytes_per_device"] * 2 == st["cache_bytes"]
    for i in range(3):
        np.testing.assert_array_equal(
            got[i], base[i], err_msg=f"req {i}: killed != uninterrupted"
        )
        np.testing.assert_array_equal(
            got[i], _solo(lm, variables, PROMPTS[i], STEPS[i]),
            err_msg=f"req {i}: killed != solo generate()",
        )
    bat.close()


@pytest.mark.parametrize("layout", ["slots", "paged"])
def test_kill_speculative_int8(lm_setup, draft_setup, sim_mesh, layout):
    """Recovery composes with the full stack: speculative mode + int8
    caches/pools. The killed run stays lossless vs solo
    generate(kv_cache_dtype='int8') on both layouts, the draft state
    re-replicates, and both quantized pytree members land at
    logical/2 per device."""
    lm, variables = lm_setup
    draft, dvars = draft_setup
    kw = dict(slots=2, kv_cache_dtype="int8", draft_lm=draft,
              draft_variables=dvars,
              speculative=SpeculativeConfig(draft_k=3))
    if layout == "paged":
        kw.update(kv_layout="paged", page_size=8)
    mon = DeviceHealthMonitor()
    bat = _tp4(lm, variables, sim_mesh, health=mon, **kw)
    r1 = bat.submit(PROMPTS[0], 9)
    r2 = bat.submit(PROMPTS[1], 7)
    bat.tick()
    mon.kill(_mesh_devices(bat)[2])
    out = bat.run()
    st = bat.stats()
    assert st["tp"] == 2 and st["recoveries"] == 1
    assert st["cache_bytes_per_device"] * 2 == st["cache_bytes"]
    # Both pytree members (int8 values AND f32 scales) head-shard to
    # exactly half per device after the reshard.
    for ck, cv in bat._caches:
        for member in (*ck, *cv) if isinstance(ck, tuple) else (ck, cv):
            from adapt_tpu.utils.profiling import device_local_nbytes

            assert device_local_nbytes(member) * 2 == member.nbytes
    for r, (p, s) in ((r1, (PROMPTS[0], 9)), (r2, (PROMPTS[1], 7))):
        np.testing.assert_array_equal(
            out[r],
            _solo(lm, variables, p, s, kv_cache_dtype="int8"),
        )
    bat.close()


def test_replay_policy_journal_roundtrip(lm_setup, sim_mesh, tmp_path):
    """policy='replay': every in-flight request re-queues from its
    JOURNALED record (payload + sampling-knob meta) instead of
    migrating — identical final tokens, ``replayed_from_journal``
    flight events with source='journal', and done marks leave the
    journal with no pending entries once drained."""
    lm, variables = lm_setup
    journal = DispatcherJournal(str(tmp_path / "wal"))
    mon = DeviceHealthMonitor()
    rec = global_flight_recorder()
    before = rec.kind_counts().get("replayed_from_journal", 0)
    bat = _tp4(
        lm, variables, sim_mesh, health=mon, slots=3, chunk=2,
        recovery=RecoveryConfig(policy="replay"), journal=journal,
    )
    got = _run_workload(bat, kill_device=_mesh_devices(bat)[1], monitor=mon)
    st = bat.stats()
    assert st["tp"] == 2
    assert st["recovery_replayed"] == 3 and st["recovery_migrated"] == 0
    for i in range(3):
        np.testing.assert_array_equal(
            got[i], _solo(lm, variables, PROMPTS[i], STEPS[i]),
            err_msg=f"replayed req {i}",
        )
    events = [
        e for e in rec.events("replayed_from_journal")
        if e["data"].get("source") == "journal"
    ]
    assert rec.kind_counts()["replayed_from_journal"] - before == 3
    assert len(events) >= 3
    # Every request finished -> done-marked: nothing pending on disk.
    _, pending, _ = journal.load()
    assert pending == {}
    bat.close()
    journal.close()


def test_replay_streams_exactly_once(lm_setup, sim_mesh):
    """A replayed request's on_token transcript has no duplicated
    prefix: indices delivered pre-kill are suppressed on the re-run
    (which regenerates them identically), later ones arrive once each
    — and the request's TTFT is not re-observed in its second life."""
    lm, variables = lm_setup
    reg = global_metrics()
    ttft0 = reg.snapshot()["histograms"].get("continuous.ttft_s", {}).get(
        "count", 0
    )
    mon = DeviceHealthMonitor()
    bat = _tp4(
        lm, variables, sim_mesh, health=mon, slots=2, chunk=2,
        recovery=RecoveryConfig(policy="replay"),
    )
    stream: list[tuple[int, int]] = []
    r = bat.submit(
        PROMPTS[0], STEPS[0],
        on_token=lambda rid, tok, idx: stream.append((idx, int(tok))),
    )
    bat.tick()
    bat.tick()  # several tokens delivered pre-kill
    assert len(stream) >= 2
    mon.kill(_mesh_devices(bat)[2])
    out = bat.run()
    assert bat.stats()["recovery_replayed"] == 1
    assert [i for i, _ in stream] == list(range(len(out[r]))), (
        "duplicated or missing stream indices across the replay"
    )
    np.testing.assert_array_equal([t for _, t in stream], out[r])
    ttft1 = reg.snapshot()["histograms"]["continuous.ttft_s"]["count"]
    assert ttft1 - ttft0 == 1, "replay re-observed TTFT"
    bat.close()


def test_replay_reenters_prefix_cache(lm_setup, sim_mesh):
    """The replay-from-prefix-cache satellite: a replayed paged request
    whose prompt spans full pages re-admits through the content-
    addressed prefix cache (``paged.prefix_hits`` increments; its
    pages were registered at the original admission and survive the
    reshard with their contents), instead of paying a full
    re-prefill."""
    lm, variables = lm_setup
    mon = DeviceHealthMonitor()
    bat = _tp4(
        lm, variables, sim_mesh, health=mon, slots=2, chunk=2,
        kv_layout="paged", page_size=8,
        recovery=RecoveryConfig(policy="replay"),
    )
    prompt = np.arange(1, 20, dtype=np.int32)  # 19 tokens: 2 full pages
    r = bat.submit(prompt, 16)
    bat.tick()
    bat.tick()
    hits0 = bat.stats()["prefix_hits"]
    mon.kill(_mesh_devices(bat)[0])  # device 0 dies; mesh rebuilds [1, 2]
    out = bat.run()
    st = bat.stats()
    assert st["recovery_replayed"] == 1
    assert st["prefix_hits"] > hits0, (
        "replayed request did not re-enter through the prefix cache"
    )
    np.testing.assert_array_equal(
        out[r], _solo(lm, variables, prompt, 16)
    )
    bat.close()


def test_dead_at_construction_detected(lm_setup, sim_mesh):
    """A device already dead on the shared monitor when the batcher is
    constructed delivers NO future 'leave' event (its lease is gone,
    and track() refuses to resurrect it) — the constructor must seed
    the loss from ``dead_ids()`` or every tick dispatches onto the
    dead chip undetected."""
    lm, variables = lm_setup
    mesh = sim_mesh(4)
    mon = DeviceHealthMonitor()
    dead = list(mesh.devices.flat)[3]
    mon.kill(dead)  # dies BEFORE the batcher exists
    bat = ContinuousBatcher(
        lm, variables, mesh=mesh, parallel=ParallelConfig(tp=4),
        health=mon, slots=2, chunk=2,
    )
    assert bat.device_lost_pending(), (
        "pre-existing dead device not detected at construction"
    )
    r = bat.submit(PROMPTS[0], STEPS[0])
    out = bat.run()
    st = bat.stats()
    assert st["tp"] == 2 and st["recoveries"] == 1
    np.testing.assert_array_equal(
        out[r], _solo(lm, variables, PROMPTS[0], STEPS[0])
    )
    bat.close()


def test_queued_cancel_of_replayed_request_keeps_delivered_stream(
    lm_setup, sim_mesh
):
    """A cancel landing while a recovery-replayed request waits for
    re-admission resolves result() with the tokens the client already
    received in its first life — not the empty array a never-admitted
    queued request gets (the stream and result() must never
    disagree)."""
    lm, variables = lm_setup
    mon = DeviceHealthMonitor()
    bat = _tp4(
        lm, variables, sim_mesh, health=mon, slots=2, chunk=2,
        recovery=RecoveryConfig(policy="replay"),
    )
    stream: list[int] = []
    r = bat.submit(
        PROMPTS[0], STEPS[0],
        on_token=lambda rid, tok, idx: stream.append(int(tok)),
    )
    bat.tick()
    bat.tick()
    assert len(stream) >= 2  # tokens delivered pre-kill
    mon.kill(_mesh_devices(bat)[2])
    bat.recover()  # replay re-queues the request; no tick yet
    assert bat.cancel(r)
    out = bat.run()
    np.testing.assert_array_equal(
        out[r], np.asarray(stream, np.int32),
        err_msg="queued cancel of a replayed request lost its "
                "delivered stream",
    )
    # Serve one more request on the shrunk mesh: the batcher survives
    # a recovery whose only in-flight request was cancelled away — and
    # the re-lowered program families compile HERE, consuming the
    # recovery's expected-compile allowances instead of leaking them
    # onto the shared class-level sentinel watches (where they would
    # absorb another batcher's real phantom-variant event).
    r2 = bat.submit(PROMPTS[1], STEPS[1])
    out2 = bat.run()
    np.testing.assert_array_equal(
        out2[r2], _solo(lm, variables, PROMPTS[1], STEPS[1])
    )
    bat.close()


def test_replay_first_new_token_itl_spans_recovery(lm_setup, sim_mesh):
    """The first post-regeneration token's ITL gap measures from the
    last token the client RECEIVED pre-kill — so a replay-policy
    recovery stall is judged against the ITL budget exactly like a
    migrated request's is, not hidden behind the regenerated prefix's
    fresh commit stamps."""
    lm, variables = lm_setup
    mon = DeviceHealthMonitor()
    bat = _tp4(
        lm, variables, sim_mesh, health=mon, slots=2, chunk=2,
        recovery=RecoveryConfig(policy="replay"),
    )
    r = bat.submit(
        PROMPTS[0], STEPS[0], slo=SLOSpec(itl_budget_s=5.0, tenant="rec")
    )
    bat.tick()
    bat.tick()
    mon.kill(_mesh_devices(bat)[1])
    bat.recover()
    req = next(q for q in bat._queue if q.req_id == r)
    assert req.t_last_delivered > 0.0, (
        "replay did not carry the pre-kill delivery stamp"
    )
    # Simulate a recovery stall far past the budget: with the gap
    # measured from the carried stamp this is an ITL miss; measured
    # from the regenerated prefix's last commit it would pass.
    req.t_last_delivered -= 100.0
    bat.run()
    assert bat.stats()["slo_itl_missed"] >= 1, (
        "kill-to-recovery stall never registered as an ITL violation"
    )
    bat.close()


@pytest.mark.parametrize("quant", ["native", "int8"])
def test_post_reshard_invariants(lm_setup, sim_mesh, quant):
    """Satellite 3: after tp=4 -> tp=2 the hot-path invariants
    re-establish — per-device KV bytes == logical/2 for BOTH pytree
    members of paged pools (native and int8), ZERO h2d per steady
    tick, and the compile sentinel sees exactly ONE re-lowered
    step-chunk variant with zero recompile EVENTS (the re-arm makes
    re-lowering expected, not phantom)."""
    from adapt_tpu.utils.profiling import device_local_nbytes

    lm, variables = lm_setup
    sentinel = global_compile_sentinel()
    mon = DeviceHealthMonitor()
    bat = _tp4(
        lm, variables, sim_mesh, health=mon, slots=2, chunk=2,
        kv_layout="paged", page_size=8, kv_cache_dtype=quant,
    )
    r1 = bat.submit(PROMPTS[0], 30)
    bat.tick()
    bat.tick()
    variants0 = sentinel.compiles("continuous.step_chunk")
    events0 = sentinel.events
    mon.kill(_mesh_devices(bat)[3])
    bat.tick()  # recovers + decodes on the shrunk mesh
    # Exactly one re-lowered decode variant; the sentinel fired NO
    # unexpected-recompile event for it (warmup re-armed).
    assert sentinel.compiles("continuous.step_chunk") - variants0 == 1
    assert sentinel.events == events0
    st = bat.stats()
    assert st["tp"] == 2
    assert st["cache_bytes_per_device"] * 2 == st["cache_bytes"]
    for ck, cv in bat._caches:
        members = (*ck, *cv) if isinstance(ck, tuple) else (ck, cv)
        for member in members:
            assert device_local_nbytes(member) * 2 == member.nbytes
    bat.tick()  # settle: first post-recovery tick re-uploads the table
    h0 = bat.stats()["h2d_transfers"]
    for _ in range(3):
        bat.tick()
    assert bat.stats()["h2d_transfers"] == h0, (
        "steady-state tick staged host arrays after the reshard"
    )
    # Churn on the shrunk mesh adds no further variants.
    variants1 = sentinel.compiles("continuous.step_chunk")
    bat.run()
    r2 = bat.submit(PROMPTS[2], 4)
    out = bat.run()
    assert set(out) == {r2} or r1 in out
    assert sentinel.compiles("continuous.step_chunk") == variants1
    assert sentinel.events == events0
    bat.close()


def test_flight_events_and_recovery_metrics(lm_setup, sim_mesh):
    """Satellite 1: the full lifecycle is visible — device_lost /
    mesh_reshard / kv_migrated flight events land in kind_counts(),
    recovery.wall_s records a histogram sample and the
    recovery.*_total counters move."""
    lm, variables = lm_setup
    rec = global_flight_recorder()
    reg = global_metrics()
    k0 = rec.kind_counts()
    snap0 = reg.snapshot()
    mon = DeviceHealthMonitor()
    bat = _tp4(lm, variables, sim_mesh, health=mon, slots=2, chunk=2)
    bat.submit(PROMPTS[0], 12)
    bat.tick()
    mon.kill(_mesh_devices(bat)[3])
    bat.run()
    k1 = rec.kind_counts()
    assert k1.get("device_lost", 0) - k0.get("device_lost", 0) == 1
    assert k1.get("mesh_reshard", 0) - k0.get("mesh_reshard", 0) == 1
    assert k1.get("kv_migrated", 0) - k0.get("kv_migrated", 0) == 1
    ev = rec.events("mesh_reshard")[-1]["data"]
    assert ev["old_tp"] == 4 and ev["new_tp"] == 2
    assert ev["moved_bytes"] > 0 and ev["host_staged_bytes"] > 0
    snap1 = reg.snapshot()
    c0 = snap0["counters"].get("recovery.migrated_total", 0.0)
    assert snap1["counters"]["recovery.migrated_total"] - c0 == 1.0
    h = snap1["histograms"]["recovery.wall_s"]
    assert h["count"] >= 1 and h["max"] > 0.0
    bat.close()


def test_auto_reshard_off_raises_then_manual_recover(lm_setup, sim_mesh):
    """auto_reshard=False: dispatches after a loss raise
    DeviceLostError (nothing runs on the broken layout) until
    recover() is called explicitly — then the stream completes
    identically."""
    lm, variables = lm_setup
    mon = DeviceHealthMonitor()
    bat = _tp4(
        lm, variables, sim_mesh, health=mon, slots=2, chunk=2,
        recovery=RecoveryConfig(auto_reshard=False),
    )
    r = bat.submit(PROMPTS[0], 12)
    bat.tick()
    mon.kill(_mesh_devices(bat)[2])
    assert bat.device_lost_pending()
    with pytest.raises(DeviceLostError, match="auto_reshard"):
        bat.tick()
    with pytest.raises(DeviceLostError):
        bat.tick()  # still broken: every dispatch raises
    bat.recover()
    out = bat.run()
    np.testing.assert_array_equal(
        out[r], _solo(lm, variables, PROMPTS[0], 12)
    )
    assert bat.stats()["tp"] == 2
    bat.close()


def test_min_tp_floor_refuses_recovery(lm_setup, sim_mesh):
    """RecoveryConfig.min_tp: survivors below the floor raise instead
    of silently serving from a remnant that cannot hold the model."""
    lm, variables = lm_setup
    mon = DeviceHealthMonitor()
    bat = _tp4(
        lm, variables, sim_mesh, health=mon, slots=2, chunk=2,
        recovery=RecoveryConfig(min_tp=2),
    )
    bat.submit(PROMPTS[0], 8)
    bat.tick()
    devs = _mesh_devices(bat)
    for d in devs[1:]:
        mon.kill(d)  # one survivor -> tp=1 < min_tp=2
    with pytest.raises(DeviceLostError, match="min_tp"):
        bat.tick()
    bat.close()


def test_triple_kill_single_device_fallback(lm_setup, sim_mesh):
    """Losing 3 of 4 chips degrades to the single-device path (the
    degenerate-mesh discipline): the stream still finishes identical
    to solo generate(), and staging lands on the SURVIVING device."""
    lm, variables = lm_setup
    mon = DeviceHealthMonitor()
    bat = _tp4(lm, variables, sim_mesh, health=mon, slots=2, chunk=2)
    r = bat.submit(PROMPTS[1], 12)
    bat.tick()
    devs = _mesh_devices(bat)
    for d in (devs[0], devs[2], devs[3]):
        mon.kill(d)
    out = bat.run()
    st = bat.stats()
    assert st["tp"] == 1 and st["recoveries"] == 1
    np.testing.assert_array_equal(
        out[r], _solo(lm, variables, PROMPTS[1], 12)
    )
    # Post-recovery traffic works end to end on the remnant.
    r2 = bat.submit(PROMPTS[0], 5)
    out = bat.run()
    np.testing.assert_array_equal(
        out[r2], _solo(lm, variables, PROMPTS[0], 5)
    )
    # Losing the LAST remnant device must raise — the degraded batcher
    # (mesh=None but still device-backed) cannot report healthy and
    # dispatch onto a dead chip.
    bat.submit(PROMPTS[2], 4)
    mon.kill(devs[1])
    assert bat.device_lost_pending()
    with pytest.raises(DeviceLostError, match="every device"):
        bat.tick()
    bat.close()


def test_mid_chunked_prefill_replays(lm_setup, sim_mesh):
    """A slot mid-chunked-prefill at kill time has emitted nothing —
    it REPLAYS (policy='migrate' notwithstanding) and still produces
    the exact stream."""
    lm, variables = lm_setup
    mon = DeviceHealthMonitor()
    bat = _tp4(
        lm, variables, sim_mesh, health=mon, slots=2, chunk=2,
        kv_layout="paged", page_size=8, prefill_chunk=8,
    )
    long_prompt = np.arange(1, 30, dtype=np.int32)  # 29 toks: 4 chunks
    r = bat.submit(long_prompt, 6)
    bat.tick()  # first prefill chunk only — nothing emitted yet
    assert bat.slots[0].pf_done >= 0
    mon.kill(_mesh_devices(bat)[1])
    out = bat.run()
    st = bat.stats()
    assert st["recovery_replayed"] == 1 and st["recovery_migrated"] == 0
    np.testing.assert_array_equal(
        out[r], _solo(lm, variables, long_prompt, 6)
    )
    bat.close()


def test_health_monitor_membership_semantics():
    """The monitor IS membership: tracked devices own registry leases,
    kill revokes exactly one, watchers see the leave, and re-tracking
    a dead device does not resurrect it."""
    mon = DeviceHealthMonitor()
    devs = jax.devices()[:4]
    mon.track(devs)
    alive = set(mon.registry.alive())
    assert {DeviceHealthMonitor.device_key(d) for d in devs} <= alive
    events = []
    mon.watch(lambda ev, key: events.append((ev, key)))
    key = mon.kill(devs[2])
    assert key == f"device:{devs[2].id}"
    assert ("leave", key) in events
    assert mon.is_dead(devs[2]) and not mon.is_dead(devs[0])
    assert mon.alive_devices(devs) == [devs[0], devs[1], devs[3]]
    mon.kill(devs[2])  # idempotent: no second leave
    assert [e for e in events if e == ("leave", key)] == [("leave", key)]
    mon.track(devs)  # dead device must not rejoin
    assert key not in set(mon.registry.alive())
    # A leave arriving from the REGISTRY side — lease expiry is the
    # production loss signal; explicit deregister exercises the same
    # watcher edge — folds into the dead set exactly like kill(), so
    # recover()'s dead_ids() view always agrees with the queued event.
    mon.registry.deregister(DeviceHealthMonitor.device_key(devs[1]))
    assert mon.is_dead(devs[1])
    assert mon.alive_devices(devs) == [devs[0], devs[3]]


@pytest.mark.slow
def test_combined_fault_kill_during_cancel_storm(lm_setup, sim_mesh):
    """Satellite 4: a device kill mid-stream CONCURRENT with a cancel
    storm while /metrics.json and /debug/events scrape continuously.
    The admit/finish books balance (every admitted request finishes,
    cancelled or not), no gauge or counter goes negative, every
    scrape parses, and exactly one reshard happened."""
    from adapt_tpu.utils.exporter import serve_metrics

    lm, variables = lm_setup
    rec = global_flight_recorder()
    server = serve_metrics(port=0)
    port = server.server_address[1]
    stop = threading.Event()
    scrapes: list[dict] = []
    scrape_errors: list[Exception] = []

    def scraper():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics.json", timeout=10
                ) as r:
                    scrapes.append(json.loads(r.read()))
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/events", timeout=10
                ) as r:
                    json.loads(r.read())
            except Exception as e:  # noqa: BLE001 — assert after join
                scrape_errors.append(e)
                return

    mon = DeviceHealthMonitor()
    bat = _tp4(lm, variables, sim_mesh, health=mon, slots=3, chunk=2)
    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    rng = np.random.RandomState(5)
    admits0 = rec.kind_counts().get("admit", 0)
    finishes0 = rec.kind_counts().get("finish", 0)
    try:
        ids = []
        cancelled = set()
        killed = False
        for wave in range(6):
            for _ in range(3):
                p = rng.randint(0, 37, size=rng.randint(2, 10)).astype(
                    np.int32
                )
                ids.append(bat.submit(p, int(rng.randint(4, 16))))
            bat.tick()
            # Storm: cancel ~half of everything in flight each wave.
            for r in ids:
                if r not in cancelled and rng.rand() < 0.5:
                    if bat.cancel(r):
                        cancelled.add(r)
            if wave == 2 and not killed:
                mon.kill(_mesh_devices(bat)[3])  # mid-storm kill
                killed = True
            bat.tick()
        bat.run()
    finally:
        stop.set()
        t.join(timeout=30)
        server.shutdown()
        server.server_close()
    assert not scrape_errors, scrape_errors
    assert scrapes, "scraper never completed a scrape"
    assert cancelled, "storm cancelled nothing"
    st = bat.stats()
    assert st["tp"] == 2 and st["recoveries"] == 1
    assert st["active"] == 0 and st["queued"] == 0
    counts = rec.kind_counts()
    admits = counts.get("admit", 0) - admits0
    finishes = counts.get("finish", 0) - finishes0
    # Every ADMITTED request produced exactly one finish edge — except
    # replayed ones, which admit twice for their single finish. The
    # books balance modulo the recorded replays; queued-cancels
    # consumed before admission appear in neither column.
    replays = st["recovery_replayed"]
    assert admits == finishes + replays, (admits, finishes, replays)
    assert counts.get("mesh_reshard", 0) >= 1
    for snap in [scrapes[-1], global_metrics().snapshot()]:
        for name, v in snap["gauges"].items():
            assert v >= 0.0, f"negative gauge {name}={v}"
        for name, v in snap["counters"].items():
            assert v >= 0.0, f"negative counter {name}={v}"
    bat.close()
